"""Headline benchmark: flagship training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Metric: training tokens/sec/chip on the flagship llama-family model
(fwd+bwd+AdamW, bf16, jit). ``vs_baseline`` is measured MFU divided by
0.45 — the Megatron-LM-class MFU the reference metadata names as its
north star ("match H100 Megatron-LM MFU", BASELINE.json). The reference
tree itself publishes no numbers (BASELINE.md), so the baseline is that
published target utilization, making vs_baseline hardware-neutral:
>1.0 means this framework utilizes its chip better than the reference
stack utilizes its own.

Resilience (the tunneled TPU backend has outages): the default mode
orchestrates — a cheap preflight probe with retry/backoff on
UNAVAILABLE, then the measurement in a subprocess per preset with a
wall-clock budget, falling back flagship-1b → flagship-420m → tiny.
Exactly one JSON line is always printed; on total failure it carries an
"error" field and rc=1. Successful runs are appended to BENCH_LOG.jsonl
so every recorded number has an in-repo artifact.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))

PEAK_BF16_FLOPS = {
    # per-chip peak bf16 FLOP/s by device_kind substring
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v4": 275e12, "v6": 918e12, "cpu": 1e12,
}

# Fallback ladder: (preset, batch, remat, subprocess wall budget seconds),
# ordered by expected MFU. "dots" (selective) remat rungs come FIRST:
# full remat re-runs the whole forward in backward, so the hardware
# spends ~4 units of matmul per 3 units the MFU formula credits —
# selective remat keeps MXU outputs and replays only elementwise/norm
# work, so nearly every hardware FLOP is a counted FLOP (see
# PROFILE.md). Sizing (measured on the 2026-07-30 live window):
# flagship-1b dots batch 4 OOMs in HLO temps (~5.7 GB of saved MXU
# outputs vs ~3.7 GB of HBM left beside the 12 GB param+grad+AdamW
# resident set) — batch 2 is the config that fits, and its d=2048
# contractions carry a higher single-chip MXU ceiling than 420m's
# d=1024 (models/config.py note). flagship-420m batch 8 dots fits
# comfortably (state ~5 GB); its full-remat sibling is the verified
# round-2 config (MFU 0.3328). tiny exists so an outage-day run still
# records *a* number rather than nothing.
LADDER = [
    ("flagship-1b", 2, "dots", 900.0),
    ("flagship-420m", 8, "dots", 900.0),
    ("flagship-420m", 8, "full", 600.0),
    ("tiny", 8, "none", 300.0),
]

# The environment's sitecustomize force-registers the tunneled TPU and
# overrides JAX_PLATFORMS, so an env var alone cannot redirect the bench;
# BENCH_PLATFORM uses jax.config (authoritative) — it exists so the
# ladder/orchestrator logic itself can be driven on CPU.
PREFLIGHT = (
    "import os, jax, jax.numpy as jnp;"
    "p = os.environ.get('BENCH_PLATFORM');"
    "p and jax.config.update('jax_platforms', p);"
    "x = jnp.ones((256, 256), jnp.bfloat16);"
    "print('PREFLIGHT_OK', float((x @ x)[0, 0]),"
    "      jax.devices()[0].device_kind)"
)


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_BF16_FLOPS.items():
        if key in kind:
            return val
    return 197e12


def _measure(args) -> None:
    """Run one measurement in this process and print the JSON line."""
    remat = {"none": False, "full": True, "dots": "dots"}[args.remat]

    import jax

    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    # Persistent compile cache: the ~1B step takes minutes to compile on
    # the tunneled backend and every bench invocation is a fresh process.
    cache_dir = os.path.join(HERE, ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax: cache simply stays off
    import jax.numpy as jnp
    from hadoop_tpu.models import count_params, get_config
    from hadoop_tpu.parallel import MeshPlan, make_mesh
    from hadoop_tpu.parallel.train import (init_sharded, make_data_sharding,
                                           make_train_step)

    cfg = get_config(args.preset, max_seq=args.seq)
    plan = MeshPlan()  # single chip
    mesh = make_mesh(plan)
    step = make_train_step(cfg, plan, mesh, remat=remat, donate=True)
    params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan, mesh)
    n_params = count_params(params)

    ds = make_data_sharding(mesh)
    key = jax.random.PRNGKey(1)
    tokens = jax.device_put(
        jax.random.randint(key, (args.batch, args.seq), 0, cfg.vocab_size,
                           dtype=jnp.int32), ds)
    targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)

    # NOTE: sync via a host transfer (float()), not block_until_ready —
    # on the tunneled axon backend block_until_ready returns early and
    # fabricates impossible throughput. The steps chain on donated
    # buffers, so one final transfer bounds the whole timed region.
    for _ in range(args.warmup):
        params, opt, metrics = step(params, opt, tokens, targets)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, opt, metrics = step(params, opt, tokens, targets)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = args.batch * args.seq
    tok_s = tokens_per_step * args.steps / dt
    # fwd+bwd matmul FLOPs: 6*N per token + causal attention term
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * args.seq * \
        cfg.d_model // 2
    mfu = tok_s * flops_per_token / peak_flops(jax.devices()[0])

    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "preset": args.preset,
        "n_params": n_params,
        "batch": args.batch,
        "seq": args.seq,
        "steps": args.steps,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "loss": round(float(metrics["loss"]), 4),
    }))


def _preflight(budget: float) -> bool:
    """Cheap backend probe with retry/backoff. True once a trivial jit
    executes on the device; False when the budget is exhausted."""
    deadline = time.monotonic() + budget
    attempt = 0
    while time.monotonic() < deadline:
        attempt += 1
        left = deadline - time.monotonic()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", PREFLIGHT],
                capture_output=True, text=True,
                timeout=max(30.0, min(150.0, left)))
            if proc.returncode == 0 and "PREFLIGHT_OK" in proc.stdout:
                print(f"# preflight ok (attempt {attempt}): "
                      f"{proc.stdout.strip()}", file=sys.stderr)
                return True
            print(f"# preflight attempt {attempt} failed rc="
                  f"{proc.returncode}: {proc.stderr.strip()[-300:]}",
                  file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"# preflight attempt {attempt} timed out",
                  file=sys.stderr)
        time.sleep(min(20.0 * attempt, max(0.0, deadline -
                                           time.monotonic())))
    return False


def _orchestrate(args) -> int:
    errors = []
    deadline = time.monotonic() + args.total_budget
    # Capture provenance BEFORE the ladder starts: a rung can run for
    # many minutes while development continues, and a number measured
    # at commit A must not be stamped with a commit that landed later.
    try:
        code_at_start = subprocess.run(
            ["git", "-C", HERE, "describe", "--always", "--dirty"],
            capture_output=True, text=True, timeout=10).stdout.strip() \
            or None
    except (OSError, subprocess.TimeoutExpired):
        code_at_start = None
    if not _preflight(args.preflight_budget):
        errors.append("preflight: backend UNAVAILABLE within budget")
        # Fall through anyway with the smallest preset — the measurement
        # subprocess is the authoritative probe and the backend may have
        # just come up.
        ladder = LADDER[-1:]
    else:
        ladder = LADDER
    backend_suspect = False
    for preset, batch, remat, budget in ladder:
        if time.monotonic() > deadline:
            errors.append("total budget exhausted")
            break
        if backend_suspect:
            # The previous rung timed out — on the tunneled backend that
            # usually means the device flapped mid-ladder (it comes and
            # goes on a minutes timescale), not that the rung was too
            # big. Don't burn the remaining rung budgets against a dead
            # device: wait for a preflight to answer again first.
            wait = min(args.preflight_budget, deadline - time.monotonic())
            if wait <= 0 or not _preflight(wait):
                errors.append("backend did not come back; stopping ladder")
                break
            backend_suspect = False
        budget = min(budget, deadline - time.monotonic())
        if budget <= 0:
            errors.append("total budget exhausted")
            break
        cmd = [sys.executable, os.path.abspath(__file__),
               "--_measure", "--preset", preset, "--batch", str(batch),
               "--remat", remat, "--seq", str(args.seq),
               "--steps", str(args.steps), "--warmup", str(args.warmup)]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=budget)
        except subprocess.TimeoutExpired:
            errors.append(f"{preset}: exceeded {budget:.0f}s budget")
            backend_suspect = True
            continue
        result = None
        for ln in proc.stdout.splitlines():
            if ln.startswith("{"):
                try:
                    parsed = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(parsed, dict) and "metric" in parsed:
                    result = parsed
        if proc.returncode == 0 and result:
            result["fallbacks"] = errors
            print(json.dumps(result))
            if os.environ.get("BENCH_PLATFORM"):
                return 0  # smoke-test run: keep it out of the TPU log
            try:
                entry = dict(result)
                entry["timestamp"] = datetime.datetime.now().isoformat(
                    timespec="seconds")
                if code_at_start:  # absent (not null) when unknown
                    entry["code"] = code_at_start
                with open(os.path.join(HERE, "BENCH_LOG.jsonl"), "a") as f:
                    f.write(json.dumps(entry) + "\n")
            except OSError:
                pass
            return 0
        errors.append(f"{preset}: rc={proc.returncode} "
                      f"{(proc.stderr or '').strip()[-300:]}")
    failure = {
        "metric": "train_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "error": "; ".join(errors)[-2000:],
    }
    # The tunneled backend's outages last hours; a failed attempt says
    # nothing about the framework. Surface the most recent verified
    # measurement (every BENCH_LOG.jsonl entry was produced by this
    # same orchestrator on the real chip and timestamped; entries since
    # commit-stamping landed also carry the commit they ran at — "code"
    # null/absent means an older, unstamped entry) so the artifact
    # records both facts: the backend was down now, AND the last number
    # that landed — clearly labelled as a PAST measurement, not this
    # tree's. Smoke runs (BENCH_PLATFORM set) stay decoupled from the
    # TPU log in both directions.
    if not os.environ.get("BENCH_PLATFORM"):
        try:
            with open(os.path.join(HERE, "BENCH_LOG.jsonl")) as f:
                lines = [ln for ln in f.read().splitlines() if ln.strip()]
            for ln in reversed(lines):  # skip a torn final append
                try:
                    past = json.loads(ln)
                except ValueError:
                    continue
                if not isinstance(past, dict):
                    continue
                # Deliberately different field names from the top-level
                # result ("tokens_per_sec", not "value"; no "metric") so
                # a consumer that regex-scans or flattens the line can't
                # mistake the past measurement for this run's.
                failure["last_verified"] = {
                    "tokens_per_sec": past.get("value"),
                    "mfu": past.get("mfu"),
                    "vs_baseline_measured": past.get("vs_baseline"),
                    "preset": past.get("preset"),
                    "device": past.get("device"),
                    "timestamp": past.get("timestamp"),
                    "code": past.get("code"),
                }
                break
        except OSError:
            pass
    print(json.dumps(failure))
    return 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="flagship-1b")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--remat", default="full",
                    choices=["none", "full", "dots"])
    ap.add_argument("--preflight-budget", type=float, default=420.0)
    ap.add_argument("--total-budget", type=float, default=5400.0,
                    help="overall wall-clock cap across rungs + backend "
                    "waits (the tunneled device flaps; waiting is often "
                    "the right spend)")
    ap.add_argument("--_measure", action="store_true",
                    help="internal: run one measurement in-process")
    args = ap.parse_args()
    if args._measure:
        _measure(args)
        return 0
    return _orchestrate(args)


if __name__ == "__main__":
    sys.exit(main())
