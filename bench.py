"""Headline benchmark: flagship training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Metric: training tokens/sec/chip on the flagship llama-family model
(fwd+bwd+AdamW, bf16, jit). ``vs_baseline`` is measured MFU divided by
0.45 — the Megatron-LM-class MFU the reference metadata names as its
north star ("match H100 Megatron-LM MFU", BASELINE.json). The reference
tree itself publishes no numbers (BASELINE.md), so the baseline is that
published target utilization, making vs_baseline hardware-neutral:
>1.0 means this framework utilizes its chip better than the reference
stack utilizes its own.
"""

from __future__ import annotations

import argparse
import json
import time

PEAK_BF16_FLOPS = {
    # per-chip peak bf16 FLOP/s by device_kind substring
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v4": 275e12, "v6": 918e12, "cpu": 1e12,
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_BF16_FLOPS.items():
        if key in kind:
            return val
    return 197e12


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="flagship-1b")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=2)
    # Default = the measured-best verified config on the v5e: the ~1B
    # flagship at batch 4 + full remat (MFU 0.527). The old 420M flagship
    # capped at MFU ~0.34 regardless of batch/remat because its d=1024
    # contractions only reach ~0.74 of MXU peak (vs ~0.90 at d=2048 —
    # measured with plain jit matmul chains); remat="none" OOMs at 1B and
    # remat="dots" fails to compile there on the tunneled backend.
    ap.add_argument("--remat", default="full",
                    choices=["none", "full", "dots"])
    args = ap.parse_args()
    remat = {"none": False, "full": True, "dots": "dots"}[args.remat]

    import os

    import jax

    # Persistent compile cache: the ~1B step takes minutes to compile on
    # the tunneled backend and every bench invocation is a fresh process.
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax: cache simply stays off
    import jax.numpy as jnp
    from hadoop_tpu.models import count_params, get_config
    from hadoop_tpu.parallel import MeshPlan, make_mesh
    from hadoop_tpu.parallel.train import (init_sharded, make_data_sharding,
                                           make_train_step)

    cfg = get_config(args.preset, max_seq=args.seq)
    plan = MeshPlan()  # single chip
    mesh = make_mesh(plan)
    step = make_train_step(cfg, plan, mesh, remat=remat, donate=True)
    params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan, mesh)
    n_params = count_params(params)

    ds = make_data_sharding(mesh)
    key = jax.random.PRNGKey(1)
    tokens = jax.device_put(
        jax.random.randint(key, (args.batch, args.seq), 0, cfg.vocab_size,
                           dtype=jnp.int32), ds)
    targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)

    # NOTE: sync via a host transfer (float()), not block_until_ready —
    # on the tunneled axon backend block_until_ready returns early and
    # fabricates impossible throughput. The steps chain on donated
    # buffers, so one final transfer bounds the whole timed region.
    for _ in range(args.warmup):
        params, opt, metrics = step(params, opt, tokens, targets)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, opt, metrics = step(params, opt, tokens, targets)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = args.batch * args.seq
    tok_s = tokens_per_step * args.steps / dt
    # fwd+bwd matmul FLOPs: 6*N per token + causal attention term
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * args.seq * \
        cfg.d_model // 2
    mfu = tok_s * flops_per_token / peak_flops(jax.devices()[0])

    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "preset": args.preset,
        "n_params": n_params,
        "batch": args.batch,
        "seq": args.seq,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "loss": round(float(metrics["loss"]), 4),
    }))


if __name__ == "__main__":
    main()
