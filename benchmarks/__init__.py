"""Storage/compute benchmark harnesses.

The reference publishes no numbers in-tree; what it ships is harnesses
(SURVEY.md §6). These are their counterparts, each a runnable one-liner
printing ONE JSON line:

  python -m benchmarks.nn_throughput   — namespace ops/sec per op type
      (ref: hadoop-hdfs src/test .../namenode/NNThroughputBenchmark.java)
  python -m benchmarks.dfsio           — DFS write/read MB/s
      (ref: hadoop-mapreduce-client-jobclient src/test .../fs/TestDFSIO.java)
  python -m benchmarks.terasort_bench  — end-to-end sort bytes/sec
      (ref: hadoop-mapreduce-examples .../terasort/TeraSort.java)
  python -m benchmarks.rpc_bench       — RPC calls/sec
      (ref: hadoop-common src/test .../ipc/RPCCallBenchmark.java)
  python -m benchmarks.run_all         — all four → STORAGE_BENCH.json
"""

import os
import tempfile


def bench_base_dir(name: str):
    """Cluster dir for benchmark runs: tmpfs when the host has one.

    Benchmarks measure the FRAMEWORK's data plane; on a single-virtual-disk
    CI host, ext4 writeback throttling (≈136 MB/s here) would otherwise cap
    every number at the VM's disk, with run-to-run variance from dirty-page
    state. Real deployments spread DNs over many disks. Tests still run on
    real disk paths.
    """
    for root in ("/dev/shm", None):
        if root is not None and not os.path.isdir(root):
            continue
        return tempfile.mkdtemp(prefix=f"htpu-bench-{name}-", dir=root)
    return None
