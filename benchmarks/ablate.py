"""MFU ablations on the real chip: which part of the step underperforms.

  python -m benchmarks.ablate
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp


def timeit(fn, *args, steps=10, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jnp.sum(jax.tree_util.tree_leaves(out)[0].astype(jnp.float32)).block_until_ready()
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0].astype(jnp.float32)))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / steps


def main():
    from hadoop_tpu.models import get_config
    from hadoop_tpu.models.decoder import ParallelCtx, forward_hidden
    cfg = get_config("flagship-420m")
    peak = 197e12
    B, S, D, F, V = 4, 2048, cfg.d_model, cfg.d_ff, cfg.vocab_size
    key = jax.random.PRNGKey(0)

    # 1. plain matmul chain at model shapes
    x = jax.random.normal(key, (B * S, D), jnp.bfloat16)
    w1 = jax.random.normal(key, (D, F), jnp.bfloat16)
    w2 = jax.random.normal(key, (F, D), jnp.bfloat16)

    @jax.jit
    def mm_chain(x):
        for _ in range(24):
            x = (x @ w1) @ w2
        return x
    dt = timeit(mm_chain, x)
    fl = 24 * 2 * (B * S) * (D * F + F * D) * 2 / 2  # 2*M*K*N per mm
    fl = 24 * (2 * B * S * D * F + 2 * B * S * F * D)
    print(f"matmul chain: {dt*1e3:.1f}ms  {fl/dt/1e12:.1f} TFLOP/s "
          f"({fl/dt/peak:.0%} of peak)")

    # 2. flash attention fwd at model shapes
    from hadoop_tpu.ops.flash import flash_attention
    q = jax.random.normal(key, (B, S, cfg.n_heads, cfg.head_dim),
                          jnp.bfloat16)
    kv = jax.random.normal(key, (B, S, cfg.n_kv_heads, cfg.head_dim),
                           jnp.bfloat16)
    fa = jax.jit(lambda q, k, v: flash_attention(q, k, v))
    dt = timeit(fa, q, kv, kv)
    fl = 2 * 2 * B * cfg.n_heads * S * S * cfg.head_dim / 2  # causal
    print(f"flash fwd:    {dt*1e3:.1f}ms  {fl/dt/1e12:.1f} TFLOP/s "
          f"({fl/dt/peak:.0%} of peak)")

    # 3. full model forward (no loss)
    from hadoop_tpu.models import init_params
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(key, (B, S), 0, V, dtype=jnp.int32)
    ctx = ParallelCtx()
    fwd = jax.jit(lambda p, t: forward_hidden(p, t, cfg, ctx))
    dt = timeit(fwd, params, tokens)
    n = 350_274_560
    fl = 2 * n * B * S + 12 * cfg.n_layers * S * D / 2 * B * S
    fl = B * S * (2 * n + 12 * cfg.n_layers * S * D / 2 / S * S)
    fl = B * S * (2 * n) + 4 * cfg.n_layers * B * cfg.n_heads * S * S * cfg.head_dim / 2
    print(f"model fwd:    {dt*1e3:.1f}ms  {fl/dt/1e12:.1f} TFLOP/s "
          f"({fl/dt/peak:.0%} of peak)")

    # 4. forward + chunked CE loss
    from hadoop_tpu.parallel.train import _loss_from_h
    from hadoop_tpu.models.decoder import forward_hidden as fh

    @jax.jit
    def fwd_loss(p, t, tg):
        h = fh(p, t, cfg, ctx)
        return _loss_from_h(p, h, tg, cfg, ctx)
    targets = jnp.roll(tokens, -1, axis=1)
    dt2 = timeit(fwd_loss, params, tokens, targets)
    fl2 = fl + 2 * B * S * D * V
    print(f"fwd+loss:     {dt2*1e3:.1f}ms  {fl2/dt2/1e12:.1f} TFLOP/s "
          f"({fl2/dt2/peak:.0%} of peak)  [CE adds {(dt2-dt)*1e3:.1f}ms]")


if __name__ == "__main__":
    main()
