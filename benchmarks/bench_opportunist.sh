#!/bin/bash
# Retry the headline bench until a number lands (the tunneled TPU
# backend flaps on a minutes-to-hours timescale; the round-4 lesson is
# that the only way to get a verified number is to keep trying all day).
# A cheap probe gates each attempt so dead-backend cycles cost ~90 s,
# not a full tiny-rung budget. Stops on first success (BENCH_LOG.jsonl
# gains a line) or when the overall deadline passes.
cd "$(dirname "$0")/.."
DEADLINE=$(( $(date +%s) + ${1:-28800} ))
ATTEMPT=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    ATTEMPT=$((ATTEMPT + 1))
    echo "=== attempt $ATTEMPT $(date -u +%H:%M:%S) ===" >> bench_opportunist.log
    if timeout 90 python -c "import jax, jax.numpy as jnp; x = jnp.ones((256,256), jnp.bfloat16); print(float((x@x)[0,0]))" \
            >> bench_opportunist.log 2>&1; then
        echo "--- probe OK, running bench ---" >> bench_opportunist.log
        python bench.py --preflight-budget 120 --total-budget 3600 \
            >> bench_opportunist.log 2>&1
        rc=$?
        if [ $rc -eq 0 ] && [ -s BENCH_LOG.jsonl ]; then
            echo "=== SUCCESS rc=$rc ===" >> bench_opportunist.log
            exit 0
        fi
    fi
    sleep 240
done
echo "=== deadline passed without a number ===" >> bench_opportunist.log
exit 1
