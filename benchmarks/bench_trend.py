"""Trajectory sentinel over BENCH_LOG.jsonl — the history's judge.

``run_all`` has appended one ``bench_suite`` row per run since ISSUE
17, and train rows have been hand-stamped far longer, but nothing ever
*read* the log: a metric could halve between issues and nobody would
know until someone re-ran a bench by hand. This module diffs the
newest row's key metrics against the recent history and flags moves
beyond a per-metric tolerance.

Judgement rules:

- The baseline for each metric is the **median of up to the last 5
  prior values** (median, not last: one outlier run must not become
  the yardstick every later run is judged against).
- Each metric has a direction (``higher`` is better for throughputs,
  ``lower`` for latencies/overheads/counts-of-bad-things) and a
  relative tolerance; metrics not in the table fall back to a suffix
  heuristic + ``DEFAULT_REL``. Only moves in the WORSE direction flag.
- Comparisons only happen between rows that actually ran the suite
  (a ``--quick`` row is only compared against other quick rows —
  sizes differ, so cross-shape diffs would be noise).

Wire-in: ``run_all`` calls :func:`check` on the row it is about to
append (recorded-not-raised — a regression is a data point in the
trajectory, not a reason to lose the run). CI-style use::

    python -m benchmarks.bench_trend --log BENCH_LOG.jsonl --check

exits 1 when the newest row regresses.
"""

from __future__ import annotations

import argparse
import json
from typing import Dict, List, Optional

DEFAULT_REL = 0.35
HISTORY = 5

# metric name (as logged: "<suite>.<name>") -> direction + tolerance.
# Direction "lower" = smaller is better. Tolerances are deliberately
# loose — the sentinel hunts step-function regressions between issues,
# not run-to-run jitter on shared CI iron.
TOLERANCES: Dict[str, Dict[str, object]] = {
    "nn_throughput_ops_per_sec.create_ops_per_sec":
        {"direction": "higher", "rel": 0.4},
    "dfsio.write_mb_s": {"direction": "higher", "rel": 0.4},
    "terasort.sort_bytes_per_sec": {"direction": "higher", "rel": 0.4},
    "serving.ttft_p50_ms": {"direction": "lower", "rel": 0.5},
    "serving_speculate.steps_ratio": {"direction": "lower", "rel": 0.3},
    "serving_quantized.capacity_ratio":
        {"direction": "higher", "rel": 0.2},
    "serving_moe.moe_tokens_per_sec":
        {"direction": "higher", "rel": 0.5},
    "serving_moe.moe_a2a_payload_ratio":
        {"direction": "lower", "rel": 0.3},
    "trace_overhead.overhead_frac": {"direction": "lower", "rel": 0.5},
    "doctor.windows_to_flag": {"direction": "lower", "rel": 0.5},
    "flight_recorder.windows_to_flag":
        {"direction": "lower", "rel": 0.5},
    "flight_elastic.lost_steps": {"direction": "lower", "rel": 0.5},
    "serving_longctx.longctx_decode_tokens_per_sec":
        {"direction": "higher", "rel": 0.5},
    "lowp.sync_exec_ratio": {"direction": "lower", "rel": 0.3},
    # hard zeroes: ANY unbaselined lint finding is a regression
    "lint.unbaselined": {"direction": "lower", "rel": 0.0},
    "lint.wall_seconds": {"direction": "lower", "rel": 1.0},
}

# suffixes that mean "smaller is better" when a metric has no table
# entry (seconds, latencies, overheads, error-ish counters)
_LOWER_SUFFIXES = ("_seconds", "_ms", "_frac", "_ratio_bad", "_lost",
                   "_sheds", "_failures", "_unbaselined",
                   "windows_to_flag", "lost_steps", "overhead_frac")


def _rule(metric: str) -> Dict[str, object]:
    rule = TOLERANCES.get(metric)
    if rule is not None:
        return rule
    lower = any(metric.endswith(s) or s in metric
                for s in _LOWER_SUFFIXES)
    return {"direction": "lower" if lower else "higher",
            "rel": DEFAULT_REL}


def load_rows(path: str) -> List[dict]:
    """All ``bench_suite`` rows of a BENCH_LOG.jsonl, oldest first
    (hand-stamped train rows and scorecards pass through untouched
    elsewhere — the sentinel only judges suite rows)."""
    rows: List[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict) and \
                        row.get("metric") == "bench_suite":
                    rows.append(row)
    except OSError:
        pass
    return rows


def check(rows: List[dict],
          tolerances: Optional[Dict[str, Dict[str, object]]] = None
          ) -> dict:
    """Judge the NEWEST row in ``rows`` against the prior history.

    Returns ``{compared, regressions, regressions_count, skipped}``;
    ``regressions`` rows carry metric / newest / baseline / ratio /
    tolerance / direction. Never raises on malformed history.
    """
    table = tolerances if tolerances is not None else TOLERANCES
    if not rows:
        return {"compared": 0, "regressions": [],
                "regressions_count": 0, "skipped": "empty log"}
    newest = rows[-1]
    quick = bool(newest.get("quick"))
    prior = [r for r in rows[:-1] if bool(r.get("quick")) == quick]
    if not prior:
        return {"compared": 0, "regressions": [],
                "regressions_count": 0,
                "skipped": "no prior rows of the same shape"}
    metrics = newest.get("key_metrics") or {}
    if not isinstance(metrics, dict):
        return {"compared": 0, "regressions": [],
                "regressions_count": 0,
                "skipped": "newest row carries no key_metrics map"}
    regressions: List[dict] = []
    compared = 0
    for metric, value in sorted(metrics.items()):
        if not isinstance(value, (int, float)) or \
                isinstance(value, bool):
            continue
        history = [r["key_metrics"][metric] for r in prior
                   if isinstance(r.get("key_metrics"), dict)
                   and isinstance(r["key_metrics"].get(metric),
                                  (int, float))
                   and not isinstance(r["key_metrics"][metric], bool)]
        if not history:
            continue                    # metric born this run
        history = history[-HISTORY:]
        baseline = sorted(history)[len(history) // 2]
        rule = table.get(metric) or _rule(metric)
        rel = float(rule.get("rel", DEFAULT_REL))
        direction = rule.get("direction", "higher")
        compared += 1
        if direction == "lower":
            # smaller is better: flag when newest exceeds the
            # baseline by more than rel (a zero baseline means any
            # positive value must clear the absolute tolerance 0)
            bound = baseline * (1.0 + rel) if baseline > 0 else 0.0
            bad = value > bound
        else:
            bound = baseline * (1.0 - rel)
            bad = value < bound
        if bad:
            regressions.append({
                "metric": metric,
                "newest": value,
                "baseline": baseline,
                "ratio": round(value / baseline, 4) if baseline
                else None,
                "tolerance_rel": rel,
                "direction": direction})
    return {"compared": compared,
            "regressions": regressions,
            "regressions_count": len(regressions)}


def append_slo_scorecard(path: str, slo: dict,
                         quick: bool = False) -> None:
    """Append one ``slo_scorecard`` row (per-class availability /
    p99 attainment / sheds / burn verdict, joined to the build hash
    the fleet's ``htpu_build_info`` gauge carries) to the trajectory
    log. Shared by ``run_all`` and ``serve_bench --storm``."""
    import time
    classes = slo.get("classes") or {}
    row = {"metric": "slo_scorecard",
           "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
           "code": slo.get("code") or "",
           "quick": quick,
           "classes": classes,
           "burning": sorted(c for c, r in classes.items()
                             if isinstance(r, dict)
                             and r.get("burning"))}
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(row) + "\n")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="judge the newest BENCH_LOG row against history")
    ap.add_argument("--log", default="BENCH_LOG.jsonl")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when the newest row regresses "
                         "(CI-style gate; default just prints)")
    args = ap.parse_args(argv)
    verdict = check(load_rows(args.log))
    print(json.dumps(verdict, indent=2))
    if args.check and verdict["regressions_count"] > 0:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
