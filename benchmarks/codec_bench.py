"""Codec throughput: MB/s per codec on shuffle-like data.

Counterpart of the reference's codec perf tests (ref:
TestCompressionStreamReuse / the lz4/snappy JNI benchmarks): measures
compress + decompress MB/s on IFile-like record data (sorted text keys
+ small binary values — compressible but not trivially so).

  python -m benchmarks.codec_bench [--mb 64]
"""

from __future__ import annotations

import argparse
import json
import os
import time


def _corpus(mb: int) -> bytes:
    # sorted word-like keys + small random values: the shape of
    # map-output spills. Every record is distinct so no codec gets a
    # free long-range-repetition win.
    out = bytearray()
    i = 0
    while len(out) < mb * 1024 * 1024:
        out += f"key-{i:010d}".encode() + b"\x00" + os.urandom(6)
        i += 1
    return bytes(out[:mb * 1024 * 1024])


def run(mb: int = 64) -> dict:
    from hadoop_tpu.io.codecs import CodecFactory
    data = _corpus(mb)
    out = {}
    for name in CodecFactory.names():
        if name in ("lzma", "bzip2"):  # minutes-per-GB archival codecs
            continue
        codec = CodecFactory.get(name)
        t0 = time.perf_counter()
        comp = codec.compress(data)
        c_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        back = codec.decompress(comp)
        d_dt = time.perf_counter() - t0
        assert back == data, name
        out[name] = {
            "compress_mb_s": round(mb / c_dt, 1),
            "decompress_mb_s": round(mb / d_dt, 1),
            "ratio": round(len(data) / len(comp), 2),
        }
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=64)
    args = ap.parse_args()
    print(json.dumps(run(args.mb)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
