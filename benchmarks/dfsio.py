"""DFS stream throughput: aggregate write/read MB/s through a minicluster.

Counterpart of the reference's TestDFSIO (ref:
hadoop-mapreduce-client-jobclient/src/test/java/org/apache/hadoop/fs/
TestDFSIO.java), shrunk to the in-process minicluster the way its own
unit mode runs: N client threads each write then read a file through the
full DFS path — NameNode block allocation, the replication pipeline
across DataNode xceivers, CRC per packet — and the aggregate MB/s is
reported.

  python -m benchmarks.dfsio [--files 4] [--mb 16] [--replication 2]
"""

from __future__ import annotations

import argparse
import json
import os
import time
from concurrent.futures import ThreadPoolExecutor


def run(n_files: int = 4, mb_per_file: int = 16, replication: int = 2,
        num_datanodes: int = 3) -> dict:
    from hadoop_tpu.testing.minicluster import MiniDFSCluster

    from hadoop_tpu.conf import Configuration

    from benchmarks import bench_base_dir

    payload = os.urandom(1024 * 1024)
    # Throughput sizing, not test sizing: real block size (the minicluster
    # default of 1 MB exists to exercise multi-block code paths in tests —
    # a 64 MB stream would pay 64 block allocations + pipeline setups).
    conf = Configuration(load_defaults=False)
    conf.set("dfs.blocksize", "64m")
    # Load-tolerant liveness (same rationale as terasort_bench): the
    # minicluster's sub-second dead detection misfires under benchmark
    # load and the resulting re-replication churn wrecks the measurement.
    conf.set("dfs.heartbeat.interval", "0.5s")
    conf.set("dfs.namenode.heartbeat.recheck-interval", "5s")
    # Bulk streaming amortizes the per-packet thread-handoff chain.
    conf.set("dfs.client-write-packet-size", str(4 * 1024 * 1024))
    base = bench_base_dir("dfsio")
    cluster = MiniDFSCluster(num_datanodes=num_datanodes, conf=conf,
                             base_dir=base)
    cluster.start()
    try:
        cluster.conf.set("dfs.replication", str(replication))
        fs = cluster.get_filesystem()

        def write_one(i: int) -> None:
            with fs.create(f"/dfsio/in_{i}") as f:
                for _ in range(mb_per_file):
                    f.write(payload)

        def read_one(i: int) -> int:
            total = 0
            with fs.open(f"/dfsio/in_{i}") as f:
                while True:
                    chunk = f.read(4 * 1024 * 1024)
                    if not chunk:
                        return total
                    total += len(chunk)

        pool = ThreadPoolExecutor(max_workers=n_files)
        t0 = time.perf_counter()
        list(pool.map(write_one, range(n_files)))
        write_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        sizes = list(pool.map(read_one, range(n_files)))
        read_dt = time.perf_counter() - t0
        pool.shutdown()
        assert all(s == mb_per_file * 1024 * 1024 for s in sizes), sizes
        total_mb = n_files * mb_per_file
        return {"write_mb_s": round(total_mb / write_dt, 1),
                "read_mb_s": round(total_mb / read_dt, 1),
                "total_mb": total_mb}
    finally:
        cluster.shutdown()
        if base:
            import shutil
            shutil.rmtree(base, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--files", type=int, default=4)
    ap.add_argument("--mb", type=int, default=16)
    ap.add_argument("--replication", type=int, default=2)
    args = ap.parse_args()
    r = run(args.files, args.mb, args.replication)
    print(json.dumps({
        "metric": "dfsio_throughput", "value": r["write_mb_s"],
        "unit": "write MB/s", **r,
        "files": args.files, "mb_per_file": args.mb,
        "replication": args.replication,
    }))


if __name__ == "__main__":
    main()
