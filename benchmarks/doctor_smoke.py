"""Fleet-doctor smoke: miniDFS + one injected-slow DataNode.

The doctor's acceptance loop, end to end over real daemons:

  1. a 3-DN miniDFS carries real write/read traffic (pipeline acks
     populate every DN's per-peer tracker);
  2. one DN gets INJECTED 250 ms pipeline-ack latencies (the detection
     decision never reads a wall-clock measurement — the absolute
     floor sits far above single-box noise);
  3. the doctor polls: exactly that DN must be flagged at
     ``/ws/v1/fleet/doctor`` within ``min-windows`` report windows,
     the NameNode must deprioritize it in pipeline placement, and an
     exemplar trace id lifted off a DN's ``/prom`` histogram must
     resolve into an assembled cross-daemon trace.

Contract failures are RECORDED in the returned dict (``failures``),
not raised — run_all keeps its prior bench results either way.

  python -m benchmarks.doctor_smoke
"""

from __future__ import annotations

import json
import re


def run(quick: bool = False) -> dict:
    import os
    import shutil
    import tempfile

    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.obs.doctor import FleetDoctor
    from hadoop_tpu.serving.autoscale.signals import http_get
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf
    from hadoop_tpu.tracing.tracer import global_tracer

    out: dict = {"failures": []}

    def check(ok: bool, what: str) -> None:
        if not ok:
            out["failures"].append(what)

    conf = fast_conf()
    conf.set("dfs.replication", "2")
    conf.set("dfs.client.read.shortcircuit", "false")
    base = tempfile.mkdtemp(
        prefix="doctor-smoke-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    doctor = None
    try:
        with MiniDFSCluster(num_datanodes=3, conf=conf,
                            base_dir=base) as cluster:
            cluster.wait_active()
            fs = cluster.get_filesystem()
            n_files = 2 if quick else 4
            for i in range(n_files):
                fs.write_all(f"/doc{i}.bin", b"\xcd" * 100_000)
                fs.read_all(f"/doc{i}.bin")
            dconf = Configuration(load_defaults=False)
            dconf.set("obs.doctor.namenode.http",
                      f"127.0.0.1:{cluster.namenode.http.port}")
            dconf.set("dfs.namenode.rpc-address",
                      f"127.0.0.1:{cluster.namenode.port}")
            dconf.set("obs.doctor.slow.floor.ms", "50")
            doctor = FleetDoctor(dconf)
            doctor.init(dconf)
            doctor.start()
            uuids = [dn.uuid for dn in cluster.datanodes]
            sick = uuids[2]
            # the injection: two healthy reporters each measure the
            # sick DN ~50x slower than each other
            for reporter in (0, 1):
                tracker = cluster.datanodes[reporter].xceiver \
                    .peer_tracker
                for _ in range(16):
                    tracker.record(sick, 0.250)
                    tracker.record(uuids[1 - reporter], 0.005)
            windows = 0
            report = {}
            for windows in range(1, 4):
                report = doctor.poll_once()
                if list(report["datanodes"]["flagged"]) == [sick]:
                    break
            flagged = sorted(report["datanodes"]["flagged"])
            out["flagged"] = [u[:8] for u in flagged]
            out["windows_to_flag"] = windows
            check(flagged == [sick],
                  f"flagged {flagged} != injected-slow [{sick}]")
            # NN placement deprioritizes the flagged node
            dm = cluster.namenode.fsn.bm.dn_manager
            check(sick in dm.slow_node_uuids(),
                  "NN never received the slow-node push")
            picks = [t.uuid for _ in range(8)
                     for t in dm.choose_targets(2, set())]
            out["placements_avoiding_sick"] = picks.count(sick) == 0
            check(sick not in picks,
                  "placement still chooses the flagged DN")
            # exemplar -> assembled trace
            with global_tracer().span("doctor.smoke.read") as root:
                fs.read_all("/doc0.bin")
            prom = http_get("127.0.0.1",
                            cluster.datanodes[0].http.port, "/prom",
                            5.0).decode()
            hexid = f"{root.trace_id:016x}"
            has_exemplar = any(
                m.group(1) == hexid for m in re.finditer(
                    r'_bucket\{[^}]*\} \d+ # \{trace_id="([0-9a-f]+)"\}',
                    prom))
            check(has_exemplar, "traced read left no /prom exemplar")
            assembled = json.loads(http_get(
                "127.0.0.1", doctor.port,
                f"/ws/v1/fleet/traces/{hexid}", 5.0))
            names = set()

            def walk(n):
                names.add(n["name"])
                for c in n["children"]:
                    walk(c)
            for r in assembled.get("tree", []):
                walk(r)
            out["assembled_spans"] = assembled.get("num_spans", 0)
            check("dfs.xceiver.read_block" in names and
                  any(n.startswith("namenode.") for n in names),
                  f"assembled trace missing planes: {sorted(names)}")
            out["critical_path"] = assembled.get("critical_path",
                                                 [])[:3]
    finally:
        if doctor is not None:
            doctor.stop()
        shutil.rmtree(base, ignore_errors=True)
    out["ok"] = not out["failures"]
    return out


def main() -> int:
    result = run()
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
