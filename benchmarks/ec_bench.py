"""Erasure-coder throughput: host Python vs native C++ vs device jit.

The RawErasureCoderBenchmark analog (ref: hadoop-common/src/test/.../
rawcoder/RawErasureCoderBenchmark.java — Java-vs-ISA-L is here
python-vs-C++-vs-XLA). All three coders share one Cauchy matrix, so
outputs are bit-identical and the comparison is pure throughput.

  python -m benchmarks.ec_bench [--mb 64] [--schema 6,3]
"""

from __future__ import annotations

import argparse
import json
import os
import time


def run(mb: int = 64, k: int = 6, m: int = 3) -> dict:
    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("EC_BENCH_PLATFORM", "cpu"))
    import jax.numpy as jnp
    import numpy as np

    from hadoop_tpu import native as nat
    from hadoop_tpu.io.erasurecode import RSRawCoder
    from hadoop_tpu.ops.ec_device import device_encoder, encode_cells

    # word-align the cell so the uint32 view below is valid at any
    # --mb/--schema combination (the odd-length path is tested via
    # encode_cells separately)
    cell = max(4, (mb * 1024 * 1024 // k) & ~3)
    cells = [os.urandom(cell) for _ in range(k)]
    total = k * cell
    out: dict = {"schema": f"RS-{k}-{m}", "data_mb": round(total / 2**20, 1)}

    t0 = time.perf_counter()
    host = RSRawCoder(k, m).encode(cells)
    out["python_encode_mb_s"] = round(total / 2**20 /
                                      (time.perf_counter() - t0), 1)

    if nat.available():
        blob = b"".join(cells)
        t0 = time.perf_counter()
        parity = nat.rs_encode(k, m, cell, blob)
        out["native_encode_mb_s"] = round(total / 2**20 /
                                          (time.perf_counter() - t0), 1)
        assert parity[:cell] == host[0], "native/host parity mismatch"

    # device: stage once, measure steady-state jit throughput (the
    # device coder targets data that is ALREADY device-resident)
    words = jnp.asarray(
        np.frombuffer(b"".join(cells), np.uint8).reshape(k, cell)
        .view(np.uint32))
    enc = device_encoder(k, m)
    jax.block_until_ready(enc(words))  # compile
    steps = 5
    t0 = time.perf_counter()
    for _ in range(steps):
        res = enc(words)
    jax.block_until_ready(res)
    out["device_encode_mb_s"] = round(steps * total / 2**20 /
                                      (time.perf_counter() - t0), 1)
    assert bytes(np.asarray(res[0]).tobytes()) == host[0], \
        "device/host parity mismatch"
    # convenience-wrapper padding path: odd-length cells must match the
    # host coder too
    odd = [c[:1021] for c in cells]
    assert encode_cells(k, m, odd) == RSRawCoder(k, m).encode(odd)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=64)
    ap.add_argument("--schema", default="6,3")
    args = ap.parse_args()
    k, m = (int(x) for x in args.schema.split(","))
    print(json.dumps(run(args.mb, k, m)))


if __name__ == "__main__":
    main()
