"""Training flight-recorder smoke: a straggler rank, caught and cleared.

The acceptance loop for the per-rank trainer telemetry plane, end to
end over real subprocess ranks:

  1. four worker subprocesses each run a real jitted comm-bearing step
     (bucketed psum over a 2-virtual-device mesh — the overlap pass's
     actual entry point, so the runtime comm ledger records REAL
     trace-time bytes and REAL dispatch walls) and publish step anatomy
     through the real ``TrainerStepMetrics`` + ``TrainerTelemetry``
     chassis (``/ws/v1/trainer``, ``/prom``, ``/ws/v1/traces``);
  2. rank 2 gets an INJECTED per-step latency (a flag file the parent
     controls — the detection decision reads only the reported means,
     and ``obs.doctor.slow.floor.ms=50`` sits far above single-box
     noise);
  3. the fleet doctor must flag exactly rank 2 at
     ``/ws/v1/fleet/doctor`` within 3 observation windows, and must
     UNFLAG it within the hysteresis history once the injection stops;
  4. the slow rank's ``htpu_comm_seconds`` histogram must show the
     collective tail (site mean >= 2x the healthy ranks') with a
     bucket exemplar whose trace id resolves through the doctor into
     an assembled trace.

Contract failures are RECORDED in the returned dict (``failures``),
not raised — run_all keeps its prior bench results either way.

Fault injection rides ``hadoop_tpu.testing.faults`` (the flag-file
API extracted from this smoke's original ad-hoc slow-file): the parent
arms per-rank kill/delay-ms/hang flags, workers call ``apply_faults``
once per step.

The ELASTIC leg (``run_elastic`` / ``--elastic``) closes the loop the
recorder only observes: a subprocess child trains a real zero1 dp=4
job, a rank is slowed (delay-ms flag → demote: protective checkpoint)
then KILLED (kill flag → evict), and the elastic controller reshards
onto dp=3 via reshard-on-restore — finishing with the loss-curve A-B
guard green against an uninterrupted dp=4 twin and strictly fewer
lost steps than the restart-from-checkpoint baseline. Needs
vma-tracking jax (the train step); no-vma boxes record
``skipped(env: no-vma)``.

  python -m benchmarks.flight_smoke             # recorder leg
  python -m benchmarks.flight_smoke --elastic   # elastic leg
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile
import time

N_RANKS = 4
SLOW_RANK = 2
DELAY_MS = 300
STEP_PACE = 0.02


# ---------------------------------------------------------------- worker

def worker_main(argv) -> int:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--port-file", required=True)
    ap.add_argument("--faults-dir", required=True)
    ap.add_argument("--stop-file", required=True)
    ap.add_argument("--max-seconds", type=float, default=120.0)
    args = ap.parse_args(argv)

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from hadoop_tpu.obs.comm import comm_runtime
    from hadoop_tpu.obs.trainer import (TrainerStepMetrics,
                                        TrainerTelemetry)
    from hadoop_tpu.parallel.overlap import bucketed_psum
    from hadoop_tpu.testing.faults import apply_faults
    from hadoop_tpu.tracing.tracer import global_tracer

    tracer = global_tracer()
    tracer.set_sample_rate(1.0)
    metrics = TrainerStepMetrics(rank=args.rank)
    telemetry = TrainerTelemetry(rank=args.rank, job="flight-smoke",
                                 metrics=metrics)
    with open(args.port_file + ".tmp", "w") as f:
        f.write(str(telemetry.port))
    os.replace(args.port_file + ".tmp", args.port_file)

    # a real comm-bearing step: matmul "work" + the overlap pass's
    # bucketed gradient psum over the 2-device mesh
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    tree = {"w": jnp.ones((32, 32)), "b": jnp.ones((64,))}
    axes = {"w": ("dp",), "b": ("dp",)}

    def body(t):
        g = {"w": t["w"] @ t["w"].T * 1e-3, "b": t["b"] * 0.5}
        return bucketed_psum(g, axes, 1 << 20)

    step = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(),),
                             out_specs=P()))
    rt = comm_runtime()
    deadline = time.monotonic() + args.max_seconds
    while time.monotonic() < deadline and \
            not os.path.exists(args.stop_file):
        t0 = time.monotonic()
        with tracer.span("trainer.step") as sp:
            sp.add_kv("rank", str(args.rank))
            with rt.step("trainer.step"):
                out = step(tree)
                jax.block_until_ready(out)
                # the injection seam: kill / delay-ms / hang flags the
                # parent arms (hadoop_tpu/testing/faults.py)
                apply_faults(args.faults_dir, args.rank)
        wall = time.monotonic() - t0
        metrics.steps.incr()
        metrics.step_wall.add(wall)
        metrics.step_wall_hist.add(wall)
        time.sleep(STEP_PACE)
    telemetry.close()
    return 0


# ---------------------------------------------------------------- parent

def run(quick: bool = False) -> dict:
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.http import http_get
    from hadoop_tpu.obs.doctor import FleetDoctor

    # quick: shorter observation windows + fewer recovery polls. The
    # rank count stays 4 — the detector's min-peers=3 needs a
    # population to be an outlier among, so that is the floor.
    window_s = 0.6 if quick else 1.0
    recovery_polls = 6 if quick else 8
    out: dict = {"failures": []}

    def check(ok: bool, what: str) -> None:
        if not ok:
            out["failures"].append(what)

    from hadoop_tpu.testing.faults import FaultInjector

    base = tempfile.mkdtemp(prefix="flight-smoke-")
    faults_dir = os.path.join(base, "faults")
    stop_file = os.path.join(base, "stop")
    inj = FaultInjector(faults_dir)
    inj.inject(SLOW_RANK, "delay-ms", str(DELAY_MS))
    procs = []
    ports = {}
    doctor = None
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)   # workers set their own device count
        for r in range(N_RANKS):
            pf = os.path.join(base, f"port-{r}")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "benchmarks.flight_smoke",
                 "--worker", "--rank", str(r), "--port-file", pf,
                 "--faults-dir", faults_dir, "--stop-file", stop_file],
                env=env, cwd=os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))))
        deadline = time.monotonic() + 90.0
        for r in range(N_RANKS):
            pf = os.path.join(base, f"port-{r}")
            while not os.path.exists(pf):
                if time.monotonic() > deadline:
                    raise RuntimeError(f"rank {r} never came up")
                if procs[r].poll() is not None:
                    raise RuntimeError(
                        f"rank {r} exited rc={procs[r].returncode}")
                time.sleep(0.2)
            with open(pf) as f:
                ports[r] = int(f.read())
        slow_name = f"rank-{SLOW_RANK}"
        conf = Configuration(load_defaults=False)
        conf.set("obs.doctor.endpoints", ",".join(
            f"rank-{r}=127.0.0.1:{ports[r]}" for r in range(N_RANKS)))
        # the absolute floor sits far above single-box noise: only the
        # injected latency can clear it (the doctor_smoke precedent)
        conf.set("obs.doctor.slow.floor.ms", "50")
        doctor = FleetDoctor(conf)
        doctor.init(conf)
        doctor.start()
        # first poll establishes the cumulative baseline (no diff yet)
        doctor.poll_once()
        time.sleep(window_s)
        windows = 0
        flagged: list = []
        for windows in range(1, 4):
            time.sleep(window_s)
            report = doctor.poll_once()
            flagged = sorted(report["trainers"]["flagged"])
            if flagged == [slow_name]:
                break
        out["windows_to_flag"] = windows
        out["flagged"] = flagged
        check(flagged == [slow_name],
              f"flagged {flagged} != injected-slow [{slow_name}]")
        ranks = report["trainers"]["ranks"]
        check(len(ranks) == N_RANKS and
              all(r.get("ok") for r in ranks.values()),
              f"roster incomplete or unhealthy: {ranks}")
        # -------- recovery: stop the injection, hysteresis must clear
        inj.clear(SLOW_RANK, "delay-ms")
        recovered_in = None
        for w in range(1, recovery_polls):
            time.sleep(window_s)
            report = doctor.poll_once()
            if not report["trainers"]["flagged"]:
                recovered_in = w
                break
        out["windows_to_recover"] = recovered_in
        check(recovered_in is not None,
              "slow rank never unflagged after the injection stopped")
        # -------- comm ledger: the slow rank's collective tail
        means = {}
        proms = {}
        for r in range(N_RANKS):
            text = http_get("127.0.0.1", ports[r], "/prom",
                            5.0).decode()
            proms[r] = text
            m = re.search(
                r'htpu_comm_seconds_sum\{[^}]*site="bucket.psum"[^}]*\} '
                r'([0-9.e+-]+)', text)
            c = re.search(
                r'htpu_comm_seconds_count\{[^}]*site="bucket.psum"'
                r'[^}]*\} ([0-9.e+-]+)', text)
            if m and c and float(c.group(1)) > 0:
                means[r] = float(m.group(1)) / float(c.group(1))
        out["comm_means_ms"] = {r: round(v * 1e3, 2)
                                for r, v in means.items()}
        healthy = [v for r, v in means.items() if r != SLOW_RANK]
        check(len(means) == N_RANKS, f"comm histograms missing: {means}")
        check(bool(healthy) and SLOW_RANK in means and
              means[SLOW_RANK] >= 2.0 * max(healthy),
              f"slow rank's comm tail not visible: {means}")
        # -------- exemplar: a slow comm bucket resolves to a trace
        ex = re.search(
            r'htpu_comm_seconds_bucket\{[^}]*\} \d+ '
            r'# \{trace_id="([0-9a-f]+)"\}', proms[SLOW_RANK])
        check(ex is not None, "no exemplar on the slow rank's "
                              "htpu_comm_seconds buckets")
        if ex is not None:
            doctor.poll_once()        # pull the rank's span ring
            status, body = 0, b""
            try:
                body = http_get("127.0.0.1", doctor.port,
                                f"/ws/v1/fleet/traces/{ex.group(1)}",
                                5.0)
                status = 200
            except IOError:
                pass
            check(status == 200, "exemplar trace did not resolve "
                                 "through the doctor")
            if status == 200:
                tree = json.loads(body)
                out["exemplar_spans"] = tree.get("num_spans")
                check(tree.get("num_spans", 0) >= 1,
                      "assembled exemplar trace is empty")
    except Exception as e:  # noqa: BLE001 — smoke harness failure is a
        # recorded data point for the trajectory, never a crash
        out["failures"].append(f"{type(e).__name__}: {e}")
    finally:
        try:
            with open(stop_file, "w") as f:
                f.write("1")
        except OSError:
            pass
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        if doctor is not None:
            doctor.stop()
        import shutil
        shutil.rmtree(base, ignore_errors=True)
    out["ok"] = not out["failures"]
    return out


# ------------------------------------------------------------ elastic leg

def _elastic_body() -> dict:
    """The elastic acceptance loop, in a process that already holds an
    8-virtual-device CPU mesh and vma-tracking jax.

    Two arms over the same token stream, tiny config, global batch 12:

    - reference: an uninterrupted zero1 dp=4 run of 36 steps;
    - elastic: the same job wired to an ElasticController. Rank 2 is
      slowed via the delay-ms flag at step 22 (→ demote: protective
      checkpoint at the next streak threshold) and KILLED via the kill
      flag at step 28 (→ evict: fence, shrink to the largest healthy
      sub-mesh dp=3 — non-power-of-two — reshard-on-restore from the
      protective snapshot, re-run the lost steps).

    The doctor FEED is scripted from the armed fault flags (the real
    FleetDoctor's detection path has its own leg above — this leg
    proves the ACTUATION half end to end): flags → trainer verdicts in
    the exact ``/ws/v1/fleet/doctor`` trainers shape the controller
    polls in production.

    Green means: loss-curve A-B guard ACCEPTED (elastic curve vs the
    uninterrupted twin, per absolute step index) and strictly fewer
    lost steps than restart-from-checkpoint (which would resume at the
    last INTERVAL save; the demote's protective snapshot is fresher).
    """
    import shutil

    import numpy as np

    from hadoop_tpu.fs import LocalFileSystem
    from hadoop_tpu.models import get_config
    from hadoop_tpu.parallel import MeshPlan
    from hadoop_tpu.parallel.checkpoint import list_checkpoints
    from hadoop_tpu.parallel.elastic import ElasticConfig
    from hadoop_tpu.parallel.lowp.guard import loss_curve_report
    from hadoop_tpu.parallel.trainer import Trainer
    from hadoop_tpu.testing.faults import FaultInjector

    N_STEPS, BATCH, INTERVAL = 36, 12, 12
    SLOW_AT, KILL_AT = 22, 28
    out: dict = {"failures": []}

    def check(ok: bool, what: str) -> None:
        if not ok:
            out["failures"].append(what)

    base = tempfile.mkdtemp(
        prefix="elastic-smoke-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    try:
        fs = LocalFileSystem()
        cfg = get_config("tiny", max_seq=32)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, cfg.vocab_size, 120_000, dtype=np.uint16)
        data_path = os.path.join(base, "tokens.bin")
        fs.write_all(data_path, toks.tobytes())
        inj = FaultInjector(os.path.join(base, "faults"))

        def poll_fn():
            # scripted doctor feed: armed flags → the trainers section
            # shape FleetDoctor.poll_once() serves (obs/doctor.py)
            flagged, ranks = {}, {}
            for r in range(4):
                dead = inj.armed(r, "kill")
                ranks[f"rank-{r}"] = {"ok": not dead, "rank": r,
                                      "job": "elastic-smoke"}
                if inj.armed(r, "delay-ms") and not dead:
                    flagged[f"rank-{r}"] = {
                        "node": f"rank-{r}",
                        "signals": ["trainer.step_wall"]}
            return {"trainers": {"flagged": flagged, "ranks": ranks}}

        # -------- reference arm: uninterrupted dp=4
        ref = Trainer(cfg, MeshPlan(dp=4), fs, data_path,
                      os.path.join(base, "ckpt-ref"), batch=BATCH,
                      lr=1e-2, zero1=True, ckpt_interval=INTERVAL)
        ref.train(N_STEPS)
        ref.wait_for_checkpoint()
        ref_curve = [ref.loss_by_step[i] for i in range(1, N_STEPS + 1)]
        ref.close()

        # -------- elastic arm: slow → demote, kill → evict, reshard
        econf = ElasticConfig(enabled=True, poll_steps=2, min_dp=1,
                              demote_windows=2, evict_windows=6,
                              dead_windows=1, cooldown_polls=2)
        ckpt_dir = os.path.join(base, "ckpt-el")
        tr = Trainer(cfg, MeshPlan(dp=4), fs, data_path, ckpt_dir,
                     batch=BATCH, lr=1e-2, zero1=True,
                     ckpt_interval=INTERVAL, elastic=econf,
                     doctor_poll=poll_fn)
        tr.train(SLOW_AT)
        inj.inject(SLOW_RANK, "delay-ms", str(DELAY_MS))
        tr.train(KILL_AT - tr.step)          # demote fires in here
        inj.inject(SLOW_RANK, "kill")
        t0 = time.monotonic()
        tr.train(N_STEPS - tr.step)          # evict + reshard + replay
        out["elastic_tail_seconds"] = round(time.monotonic() - t0, 2)
        tr.wait_for_checkpoint()
        el_curve = [tr.loss_by_step[i] for i in range(1, N_STEPS + 1)]

        events = tr.elastic.events
        by_kind = {}
        for ev in events:
            by_kind.setdefault(ev["decision"], []).append(ev)
        out["events"] = [{k: ev[k] for k in ev
                          if k not in ("config",)} for ev in events]
        check(len(by_kind.get("demote", [])) == 1,
              f"expected exactly one demote: {by_kind.keys()}")
        check(len(by_kind.get("evict", [])) == 1,
              f"expected exactly one evict: {by_kind.keys()}")
        resumes = by_kind.get("resume", [])
        check(len(resumes) == 1 and resumes[0]["restored"],
              f"expected one restoring resume: {resumes}")
        check(tr.plan.dp == 3,
              f"largest healthy sub-mesh should be dp=3 (non-power-of-"
              f"two), got {tr.plan}")
        check(tr.step == N_STEPS, f"elastic arm ended at {tr.step}")

        # lost steps: elastic resumes from the demote's protective
        # snapshot; a restart-from-checkpoint baseline resumes from
        # the newest INTERVAL save before the kill
        if resumes:
            evict_step = by_kind["evict"][0]["step"]
            out["lost_steps"] = resumes[0]["lost_steps"]
            out["resume_seconds"] = resumes[0]["resume_seconds"]
            out["lost_steps_baseline"] = \
                evict_step - (evict_step // INTERVAL) * INTERVAL
            check(out["lost_steps"] < out["lost_steps_baseline"],
                  f"elastic lost {out['lost_steps']} steps, restart "
                  f"baseline loses {out['lost_steps_baseline']}")
            # the baseline's interval checkpoint must really exist —
            # the comparison is against a restartable state, not air
            steps_on_disk = list_checkpoints(fs, ckpt_dir)
            check((evict_step // INTERVAL) * INTERVAL in steps_on_disk,
                  f"baseline interval checkpoint missing: "
                  f"{steps_on_disk}")
        out["evictions"] = len(by_kind.get("evict", []))

        guard = loss_curve_report(ref_curve, el_curve, rel_tol=0.25)
        out["guard"] = {k: guard[k] for k in
                        ("accepted", "max_rel_div", "final_rel_div")
                        if k in guard}
        check(bool(guard.get("accepted")),
              f"loss-curve guard rejected the elastic arm: {guard}")
        tr.close()
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory, never a crash
        out["failures"].append(f"{type(e).__name__}: {e}")
    finally:
        shutil.rmtree(base, ignore_errors=True)
    out["ok"] = not out["failures"]
    return out


def elastic_child_main() -> int:
    """Subprocess entry: force the 8-device CPU mesh BEFORE jax loads,
    then run the elastic body (or record the no-vma skip)."""
    from __graft_entry__ import _force_cpu_devices
    _force_cpu_devices(8)
    import jax
    if not hasattr(jax, "typeof"):
        # this box's jax cannot trace the multichip train step (see
        # __graft_entry__.dryrun precedent): record the skip, stay green
        print("ELASTIC_SMOKE " + json.dumps(
            {"skipped": "env: no-vma", "ok": True}))
        return 0
    print("ELASTIC_SMOKE " + json.dumps(_elastic_body()))
    return 0


def run_elastic(quick: bool = False, timeout_s: float = 900.0) -> dict:
    """Parent wrapper for the elastic leg (run_all records, never
    raises). ``quick`` is accepted for signature parity — the leg is
    one fixed tiny scenario either way."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)   # the child sets its own device count
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.flight_smoke",
         "--elastic-child"],
        capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for line in proc.stdout.splitlines():
        if line.startswith("ELASTIC_SMOKE "):
            return json.loads(line[len("ELASTIC_SMOKE "):])
    raise RuntimeError(
        f"elastic smoke produced no record (rc={proc.returncode}): "
        f"{proc.stderr.strip()[-2000:]}")


def main() -> int:
    if "--worker" in sys.argv:
        argv = [a for a in sys.argv[1:] if a != "--worker"]
        return worker_main(argv)
    if "--elastic-child" in sys.argv:
        return elastic_child_main()
    if "--elastic" in sys.argv:
        result = run_elastic()
        print(json.dumps(result, indent=2))
        return 0 if result.get("ok") else 1
    result = run()
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
