"""Training flight-recorder smoke: a straggler rank, caught and cleared.

The acceptance loop for the per-rank trainer telemetry plane, end to
end over real subprocess ranks:

  1. four worker subprocesses each run a real jitted comm-bearing step
     (bucketed psum over a 2-virtual-device mesh — the overlap pass's
     actual entry point, so the runtime comm ledger records REAL
     trace-time bytes and REAL dispatch walls) and publish step anatomy
     through the real ``TrainerStepMetrics`` + ``TrainerTelemetry``
     chassis (``/ws/v1/trainer``, ``/prom``, ``/ws/v1/traces``);
  2. rank 2 gets an INJECTED per-step latency (a flag file the parent
     controls — the detection decision reads only the reported means,
     and ``obs.doctor.slow.floor.ms=50`` sits far above single-box
     noise);
  3. the fleet doctor must flag exactly rank 2 at
     ``/ws/v1/fleet/doctor`` within 3 observation windows, and must
     UNFLAG it within the hysteresis history once the injection stops;
  4. the slow rank's ``htpu_comm_seconds`` histogram must show the
     collective tail (site mean >= 2x the healthy ranks') with a
     bucket exemplar whose trace id resolves through the doctor into
     an assembled trace.

Contract failures are RECORDED in the returned dict (``failures``),
not raised — run_all keeps its prior bench results either way.

  python -m benchmarks.flight_smoke
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile
import time

N_RANKS = 4
SLOW_RANK = 2
SLOW_SECONDS = 0.30
STEP_PACE = 0.02


# ---------------------------------------------------------------- worker

def worker_main(argv) -> int:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--port-file", required=True)
    ap.add_argument("--slow-file", required=True)
    ap.add_argument("--stop-file", required=True)
    ap.add_argument("--max-seconds", type=float, default=120.0)
    args = ap.parse_args(argv)

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from hadoop_tpu.obs.comm import comm_runtime
    from hadoop_tpu.obs.trainer import (TrainerStepMetrics,
                                        TrainerTelemetry)
    from hadoop_tpu.parallel.overlap import bucketed_psum
    from hadoop_tpu.tracing.tracer import global_tracer

    tracer = global_tracer()
    tracer.set_sample_rate(1.0)
    metrics = TrainerStepMetrics(rank=args.rank)
    telemetry = TrainerTelemetry(rank=args.rank, job="flight-smoke",
                                 metrics=metrics)
    with open(args.port_file + ".tmp", "w") as f:
        f.write(str(telemetry.port))
    os.replace(args.port_file + ".tmp", args.port_file)

    # a real comm-bearing step: matmul "work" + the overlap pass's
    # bucketed gradient psum over the 2-device mesh
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    tree = {"w": jnp.ones((32, 32)), "b": jnp.ones((64,))}
    axes = {"w": ("dp",), "b": ("dp",)}

    def body(t):
        g = {"w": t["w"] @ t["w"].T * 1e-3, "b": t["b"] * 0.5}
        return bucketed_psum(g, axes, 1 << 20)

    step = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(),),
                             out_specs=P()))
    rt = comm_runtime()
    deadline = time.monotonic() + args.max_seconds
    while time.monotonic() < deadline and \
            not os.path.exists(args.stop_file):
        t0 = time.monotonic()
        with tracer.span("trainer.step") as sp:
            sp.add_kv("rank", str(args.rank))
            with rt.step("trainer.step"):
                out = step(tree)
                jax.block_until_ready(out)
                if os.path.exists(args.slow_file):
                    time.sleep(SLOW_SECONDS)   # the injection
        wall = time.monotonic() - t0
        metrics.steps.incr()
        metrics.step_wall.add(wall)
        metrics.step_wall_hist.add(wall)
        time.sleep(STEP_PACE)
    telemetry.close()
    return 0


# ---------------------------------------------------------------- parent

def run(quick: bool = False) -> dict:
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.http import http_get
    from hadoop_tpu.obs.doctor import FleetDoctor

    # quick: shorter observation windows + fewer recovery polls. The
    # rank count stays 4 — the detector's min-peers=3 needs a
    # population to be an outlier among, so that is the floor.
    window_s = 0.6 if quick else 1.0
    recovery_polls = 6 if quick else 8
    out: dict = {"failures": []}

    def check(ok: bool, what: str) -> None:
        if not ok:
            out["failures"].append(what)

    base = tempfile.mkdtemp(prefix="flight-smoke-")
    slow_file = os.path.join(base, "slow")
    stop_file = os.path.join(base, "stop")
    with open(slow_file, "w") as f:
        f.write("1")
    procs = []
    ports = {}
    doctor = None
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)   # workers set their own device count
        for r in range(N_RANKS):
            pf = os.path.join(base, f"port-{r}")
            sf = slow_file if r == SLOW_RANK else \
                os.path.join(base, "never")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "benchmarks.flight_smoke",
                 "--worker", "--rank", str(r), "--port-file", pf,
                 "--slow-file", sf, "--stop-file", stop_file],
                env=env, cwd=os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))))
        deadline = time.monotonic() + 90.0
        for r in range(N_RANKS):
            pf = os.path.join(base, f"port-{r}")
            while not os.path.exists(pf):
                if time.monotonic() > deadline:
                    raise RuntimeError(f"rank {r} never came up")
                if procs[r].poll() is not None:
                    raise RuntimeError(
                        f"rank {r} exited rc={procs[r].returncode}")
                time.sleep(0.2)
            with open(pf) as f:
                ports[r] = int(f.read())
        slow_name = f"rank-{SLOW_RANK}"
        conf = Configuration(load_defaults=False)
        conf.set("obs.doctor.endpoints", ",".join(
            f"rank-{r}=127.0.0.1:{ports[r]}" for r in range(N_RANKS)))
        # the absolute floor sits far above single-box noise: only the
        # injected latency can clear it (the doctor_smoke precedent)
        conf.set("obs.doctor.slow.floor.ms", "50")
        doctor = FleetDoctor(conf)
        doctor.init(conf)
        doctor.start()
        # first poll establishes the cumulative baseline (no diff yet)
        doctor.poll_once()
        time.sleep(window_s)
        windows = 0
        flagged: list = []
        for windows in range(1, 4):
            time.sleep(window_s)
            report = doctor.poll_once()
            flagged = sorted(report["trainers"]["flagged"])
            if flagged == [slow_name]:
                break
        out["windows_to_flag"] = windows
        out["flagged"] = flagged
        check(flagged == [slow_name],
              f"flagged {flagged} != injected-slow [{slow_name}]")
        ranks = report["trainers"]["ranks"]
        check(len(ranks) == N_RANKS and
              all(r.get("ok") for r in ranks.values()),
              f"roster incomplete or unhealthy: {ranks}")
        # -------- recovery: stop the injection, hysteresis must clear
        os.remove(slow_file)
        recovered_in = None
        for w in range(1, recovery_polls):
            time.sleep(window_s)
            report = doctor.poll_once()
            if not report["trainers"]["flagged"]:
                recovered_in = w
                break
        out["windows_to_recover"] = recovered_in
        check(recovered_in is not None,
              "slow rank never unflagged after the injection stopped")
        # -------- comm ledger: the slow rank's collective tail
        means = {}
        proms = {}
        for r in range(N_RANKS):
            text = http_get("127.0.0.1", ports[r], "/prom",
                            5.0).decode()
            proms[r] = text
            m = re.search(
                r'htpu_comm_seconds_sum\{[^}]*site="bucket.psum"[^}]*\} '
                r'([0-9.e+-]+)', text)
            c = re.search(
                r'htpu_comm_seconds_count\{[^}]*site="bucket.psum"'
                r'[^}]*\} ([0-9.e+-]+)', text)
            if m and c and float(c.group(1)) > 0:
                means[r] = float(m.group(1)) / float(c.group(1))
        out["comm_means_ms"] = {r: round(v * 1e3, 2)
                                for r, v in means.items()}
        healthy = [v for r, v in means.items() if r != SLOW_RANK]
        check(len(means) == N_RANKS, f"comm histograms missing: {means}")
        check(bool(healthy) and SLOW_RANK in means and
              means[SLOW_RANK] >= 2.0 * max(healthy),
              f"slow rank's comm tail not visible: {means}")
        # -------- exemplar: a slow comm bucket resolves to a trace
        ex = re.search(
            r'htpu_comm_seconds_bucket\{[^}]*\} \d+ '
            r'# \{trace_id="([0-9a-f]+)"\}', proms[SLOW_RANK])
        check(ex is not None, "no exemplar on the slow rank's "
                              "htpu_comm_seconds buckets")
        if ex is not None:
            doctor.poll_once()        # pull the rank's span ring
            status, body = 0, b""
            try:
                body = http_get("127.0.0.1", doctor.port,
                                f"/ws/v1/fleet/traces/{ex.group(1)}",
                                5.0)
                status = 200
            except IOError:
                pass
            check(status == 200, "exemplar trace did not resolve "
                                 "through the doctor")
            if status == 200:
                tree = json.loads(body)
                out["exemplar_spans"] = tree.get("num_spans")
                check(tree.get("num_spans", 0) >= 1,
                      "assembled exemplar trace is empty")
    except Exception as e:  # noqa: BLE001 — smoke harness failure is a
        # recorded data point for the trajectory, never a crash
        out["failures"].append(f"{type(e).__name__}: {e}")
    finally:
        try:
            with open(stop_file, "w") as f:
                f.write("1")
        except OSError:
            pass
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        if doctor is not None:
            doctor.stop()
        import shutil
        shutil.rmtree(base, ignore_errors=True)
    out["ok"] = not out["failures"]
    return out


def main() -> int:
    if "--worker" in sys.argv:
        argv = [a for a in sys.argv[1:] if a != "--worker"]
        return worker_main(argv)
    result = run()
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
