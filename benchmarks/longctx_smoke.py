"""Long-context serving smoke: a prompt 8x one chip's KV budget, end
to end through the real door.

Runs in a SUBPROCESS with an 8-virtual-device CPU mesh (the
minicluster philosophy: real protocols, simulated fleet) so the parent
bench process keeps its own device topology. The contract, all
recorded in the JSON and collected into ``failed``:

- a prompt >= 8x the engine's usable KV pool (at the fixed
  ``serving.kv.hbm.bytes`` budget) POSTs through ``/v1/generate`` and
  the decoded tokens EXACTLY match a single-chip ``decoder.forward``
  greedy reference (raw KV codec arm);
- the CP prefill guards accept: exact at a small shape for ring AND
  ulysses (``run_weight_ab``-style), relaxed logits guard at the
  monster shape;
- the KV chain streamed into the tiers and paged back: host-ring hits
  AND DFS hits AND DFS persists all > 0 (the host ring is sized
  smaller than the chain on purpose), ``chain_ingested`` equals the
  full-block count;
- compile-once: the plane's prefill executable traced once, every
  paged-decode jit traced once, and a short prompt through the same
  door still rides the fused step at exactly one trace per shape;
- TTFT per CP width (1/2/4/8 chips) recorded — on the shared-core CPU
  sim the wall-clock scaling is NOT asserted (all "chips" are one
  host), the numbers are the trajectory for real-hardware runs.

An int8-codec arm re-runs the monster prompt with the KV chain stored
int8 in the host ring (relaxed guard accepted; token match vs the raw
arm recorded, not asserted — codec noise may legitimately flip a
near-tie greedy pick).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _reference_greedy(params, cfg, prompt, n):
    import jax.numpy as jnp

    from hadoop_tpu.models.decoder import forward
    ctx = list(prompt)
    out = []
    for _ in range(n):
        lg = forward(params, jnp.asarray(ctx, jnp.int32)[None, :],
                     cfg)[0, -1]
        tok = int(jnp.argmax(lg))
        out.append(tok)
        ctx.append(tok)
    return out


def _post(port, payload, timeout=600.0):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/generate",
                     body=json.dumps(payload).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, json.loads(body)
    finally:
        conn.close()


def child(quick: bool = False) -> dict:
    import tempfile

    import jax
    import numpy as np

    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.models.decoder import init_params
    from hadoop_tpu.parallel.lowp.guard import ParityGuardError
    from hadoop_tpu.serving.engine import DecodeEngine
    from hadoop_tpu.serving.longctx import (ContextParallelPrefiller,
                                            LongContextPlane,
                                            run_prefill_ab)
    from hadoop_tpu.serving.longctx.decode import trace_counts
    from hadoop_tpu.serving.metrics import ServingMetrics
    from hadoop_tpu.serving.server import ServingServer
    from hadoop_tpu.serving.weightplane import describe_tree
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    n_dev = len(jax.devices())
    assert n_dev >= 8, f"need the 8-virtual-device mesh, got {n_dev}"
    bs = 8
    prompt_len = 1024 if quick else 2048
    pool_blocks = prompt_len // bs // 8   # prompt = 8x usable pool
    max_new = 6
    cfg = get_config("tiny", max_seq=prompt_len + 256)
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, size=prompt_len).tolist()
    block_nbytes = (2 * cfg.n_layers * bs * cfg.n_kv_heads *
                    cfg.head_dim * np.dtype(cfg.dtype).itemsize)
    weight_bytes = describe_tree(params)["weight_bytes"]
    hbm_bytes = weight_bytes + (pool_blocks + 1) * block_nbytes
    # host ring holds only a quarter of the chain: decode MUST hit the
    # DFS tier for the head of the context
    host_bytes = (prompt_len // bs // 4) * block_nbytes
    out: dict = {"prompt_tokens": prompt_len, "block_size": bs,
                 "kv_pool_blocks": pool_blocks,
                 "kv_pool_tokens": pool_blocks * bs,
                 "prompt_over_pool": prompt_len / (pool_blocks * bs),
                 "hbm_bytes": hbm_bytes, "host_bytes": host_bytes}
    failed = []

    dconf = fast_conf()
    dconf.set("dfs.replication", "1")
    ref = _reference_greedy(params, cfg, prompt, max_new)
    out["reference_tokens"] = ref
    with tempfile.TemporaryDirectory() as tmp, \
            MiniDFSCluster(num_datanodes=1, conf=dconf,
                           base_dir=os.path.join(tmp, "dfs")) as c:
        c.wait_active()
        engine = DecodeEngine(
            params, cfg, block_size=bs, max_context=64,
            prefill_chunk=8, hbm_bytes=hbm_bytes,
            kv_host_bytes=host_bytes, kv_store_fs=c.get_filesystem(),
            kv_store_dir="/kvcache", metrics=ServingMetrics())
        plane = LongContextPlane(
            params, cfg, engine.kvstore, block_size=bs,
            min_tokens=512, max_tokens=prompt_len, sp=8,
            window_blocks=4, tail_tokens=64, metrics=engine.metrics)
        engine.attach_longctx(plane)
        engine.start()
        server = ServingServer(engine, Configuration())
        server.start()
        try:
            t0 = time.monotonic()
            status, resp = _post(server.port,
                                 {"tokens": prompt,
                                  "max_new_tokens": max_new,
                                  "timeout": 590})
            door_wall = time.monotonic() - t0
            out["door_status"] = status
            out["door_tokens"] = resp.get("tokens")
            out["door_wall_seconds"] = round(door_wall, 3)
            if status != 200:
                failed.append(f"door returned {status}: {resp}")
            elif resp.get("tokens") != ref:
                failed.append(
                    f"door tokens {resp.get('tokens')} != single-chip "
                    f"reference {ref}")
            # a short prompt beside the monster: the fused step still
            # compiles exactly once per shape, untouched by the plane
            status2, resp2 = _post(server.port,
                                   {"tokens": prompt[:24],
                                    "max_new_tokens": 3,
                                    "timeout": 120})
            if status2 != 200:
                failed.append(f"short-prompt door returned {status2}")
            kv = engine.kvstore.stats()
            out["kv"] = kv
            if kv["hits_host"] <= 0:
                failed.append("no host-tier hits paging the chain")
            if kv["hits_dfs"] <= 0:
                failed.append("no DFS-tier hits paging the chain "
                              "(ring sized to force them)")
            if kv["dfs_persists"] <= 0:
                failed.append("no DFS persists of the streamed chain")
            if kv["chain_ingested"] != prompt_len // bs:
                failed.append(
                    f"chain_ingested {kv['chain_ingested']} != "
                    f"{prompt_len // bs}")
            st = plane.stats()
            out["longctx"] = st
            if st["prefill_compiles"] != 1:
                failed.append(f"CP prefill traced "
                              f"{st['prefill_compiles']}x (pinned: 1)")
            bad = {k: v for k, v in trace_counts().items() if v != 1}
            if bad:
                failed.append(f"paged-decode retracing: {bad}")
            if engine.decode_compiles != 1 or \
                    engine.prefill_compiles != 1:
                failed.append(
                    f"fused step shapes traced decode="
                    f"{engine.decode_compiles} prefill="
                    f"{engine.prefill_compiles} (pinned: 1 each)")
        finally:
            server.stop()

    # ---- guards: exact at small shape (ring + ulysses), relaxed at
    # the monster shape
    small = rng.integers(0, cfg.vocab_size, size=150).tolist()
    for mode, sp in (("ring", 4), ("ulysses", 2)):
        try:
            pre = ContextParallelPrefiller(
                params, cfg, block_size=bs, pad_tokens=len(small) + 10,
                sp=sp, sp_mode=mode)
            out[f"guard_exact_{mode}"] = run_prefill_ab(
                params, cfg, small, pre, mode="exact")
        except ParityGuardError as e:
            failed.append(f"exact {mode} guard rejected: {e}")
    # ---- TTFT vs chips at the monster shape (+ the big-shape relaxed
    # guard off the 8-chip arm)
    ttft = {}
    for sp in (1, 2, 4, 8):
        pre = ContextParallelPrefiller(params, cfg, block_size=bs,
                                       pad_tokens=prompt_len, sp=sp)
        pre.cp_prefill(prompt)          # warm (the one trace)
        secs = min(pre.cp_prefill(prompt).seconds for _ in range(2))
        ttft[str(sp)] = round(secs, 4)
        if sp == 8:
            try:
                out["guard_relaxed_big"] = run_prefill_ab(
                    params, cfg, prompt, pre, mode="relaxed",
                    rel_tol=0.05)
            except ParityGuardError as e:
                failed.append(f"relaxed big-shape guard rejected: {e}")
    out["ttft_by_chips_seconds"] = ttft
    out["ttft_note"] = ("CPU-sim chips share one host's cores: "
                        "wall-clock scaling is recorded, not asserted")

    # ---- decode throughput rung: the pipelined/fused path vs the
    # legacy per-(layer, window) loop, at 1x and 8x the engine's KV
    # pool. The dispatch and transfer budgets are asserted — they are
    # deterministic counters; tokens/s is recorded as data (CPU-sim
    # walls, the TTFT convention).
    from hadoop_tpu.serving.engine import SamplingParams
    from hadoop_tpu.serving.longctx.decode import WorkingSetDecoder

    engine_d = DecodeEngine(
        params, cfg, block_size=bs, max_context=64, prefill_chunk=8,
        kv_host_bytes=(2 * prompt_len // bs + 8) * block_nbytes,
        metrics=ServingMetrics())
    short_ctx = pool_blocks * bs          # 1x the engine's usable pool
    decode = {}
    for label, toks in (("1x", prompt[:short_ctx]), ("8x", prompt)):
        res = pre.cp_prefill(toks)        # the warmed sp=8 executable
        engine_d.kvstore.ingest_chain(toks, res.blocks)
        first = int(np.argmax(res.last_logits))
        n_win = -(-len(toks) // (4 * bs))
        arms = {}
        for path, pipeline in (("pipelined", True), ("legacy", False)):
            dec = WorkingSetDecoder(
                params, cfg, engine_d.kvstore, block_size=bs,
                window_blocks=4, tail_tokens=64, pipeline=pipeline)
            got = []
            dec.paged_decode(toks, first,
                             SamplingParams(max_new_tokens=2),
                             deliver=got.append, seed=1)    # warm
            t0 = time.monotonic()
            emitted = dec.paged_decode(toks, first,
                                       SamplingParams(max_new_tokens=9),
                                       deliver=got.append, seed=1)
            wall = time.monotonic() - t0
            arms[path] = {
                "tokens_per_sec": round(emitted / max(wall, 1e-9), 2),
                "dispatches_per_token":
                    round(dec.dispatches_per_token, 2),
                "window_fetches": dec.window_fetches,
                "hbm_window_bytes": dec.hbm_window_bytes,
            }
            if pipeline and dec.dispatches_per_token > 2 * n_win + 1:
                failed.append(
                    f"{label} fused dispatches/token "
                    f"{dec.dispatches_per_token:.1f} over the 2 per "
                    f"(token, window) + head budget {2 * n_win + 1}")
        if arms["pipelined"]["window_fetches"] >= \
                arms["legacy"]["window_fetches"]:
            failed.append(
                f"{label}: pipelined slab transfers not below the "
                f"legacy per-(layer, window) slices")
        if arms["pipelined"]["dispatches_per_token"] >= \
                arms["legacy"]["dispatches_per_token"]:
            failed.append(f"{label}: fusion did not reduce dispatches "
                          f"per token")
        decode[label] = arms
    engine_d.stop()
    out["decode"] = decode
    f8, f1 = decode["8x"]["pipelined"], decode["1x"]["pipelined"]
    out["decode_tokens_per_sec"] = f8["tokens_per_sec"]
    out["decode_dispatches_per_token"] = f8["dispatches_per_token"]
    out["decode_hbm_window_bytes"] = f8["hbm_window_bytes"]
    out["decode_slowdown_8x_vs_1x"] = round(
        f1["tokens_per_sec"] / max(f8["tokens_per_sec"], 1e-9), 2)
    out["decode_note"] = ("CPU-sim walls: tokens/s recorded, not "
                          "asserted; the dispatch/transfer budgets are "
                          "asserted on their deterministic counters")

    # ---- int8 codec arm: chain stored int8 in the host ring
    engine8 = DecodeEngine(
        params, cfg, block_size=bs, max_context=64, prefill_chunk=8,
        hbm_bytes=hbm_bytes,
        kv_host_bytes=(prompt_len // bs + 8) * block_nbytes,
        kv_codec="int8", metrics=ServingMetrics())
    plane8 = LongContextPlane(
        params, cfg, engine8.kvstore, block_size=bs, min_tokens=512,
        max_tokens=prompt_len, sp=8, window_blocks=4, tail_tokens=64,
        metrics=engine8.metrics)
    engine8.attach_longctx(plane8)
    req = engine8.submit(prompt, SamplingParams(max_new_tokens=max_new))
    try:
        toks8 = req.wait(300)
        out["int8_tokens"] = toks8
        out["int8_matches_raw"] = toks8 == ref   # recorded, not asserted
    except (RuntimeError, TimeoutError) as e:
        failed.append(f"int8-codec arm failed to decode: {e}")
    engine8.stop()

    out["failed"] = failed
    return out


def run(quick: bool = False) -> dict:
    """Spawn the smoke in its own 8-virtual-device process and return
    its JSON (the run_all entry — recorded, not raised)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, "-m", "benchmarks.longctx_smoke", "--child"]
    if quick:
        cmd.append("--quick")
    proc = subprocess.run(
        cmd, cwd=os.path.dirname(os.path.dirname(os.path.abspath(
            __file__))), env=env, capture_output=True, text=True,
        timeout=1800)
    if proc.returncode != 0:
        return {"error": f"child exited {proc.returncode}",
                "stderr": proc.stderr[-2000:]}
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    return {"error": "no JSON in child stdout",
            "stdout": proc.stdout[-2000:]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true",
                    help="run the smoke in THIS process (expects the "
                         "8-virtual-device env)")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args(argv)
    if args.child:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        result = child(quick=args.quick)
    else:
        result = run(quick=args.quick)
    print(json.dumps(result))
    return 1 if (result.get("failed") or result.get("error")) else 0


if __name__ == "__main__":
    sys.exit(main())
