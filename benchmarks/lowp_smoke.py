"""CPU-mesh relaxed-parity smoke: loss-curve A-B + comm-byte contract.

Runs (in a SUBPROCESS, so the 8-virtual-device XLA flags are set before
jax initializes — same trick as overlap_smoke) the relaxed parity
tier's acceptance ladder on the tiny config:

- **dp2×tp2(+sp), 50 steps** — quantized tp reduces + true chunked
  collective matmul vs the bitwise tier; the loss-curve guard
  (parallel/lowp/guard.py) must accept the trajectory.
- **zero1 dp8, 50 steps** — quantized ZeRO-1 param reassembly; guard
  must accept AND the comm ledger must show ≥2× fewer collective
  payload bytes on the quantized buckets.
- **dp2×pp2 manual schedule, 12 steps** — quantized GRADIENT buckets
  (the bucketed psum path only the manual schedule exercises); ≥2×
  payload reduction asserted here too.
- **partial-sync schedule, dp2×tp2+sp, 50 steps** — the partially-
  synchronized activation schedule (parallel/lowp/syncpolicy.py) at
  ``periodic:2``: the loss-curve guard must accept, the ledger must
  show the scheduled tp sites executing ≥1.8× fewer collectives per
  step than the full-schedule relaxed rung, and the falsifiability
  arm (``none`` — every sync skipped) must REJECT.
- **bitwise is byte-identical** — a step built with parity=BITWISE
  must produce bit-identical losses to a step built with parity
  unset, proving zero lowp code executes on the default tier.

Mirrors the overlap_smoke contract in run_all.py: a failure is
recorded as data, never a reason to lose the other benches. The full
reports (loss trajectories, divergence, payload bytes) land in the
JSON so the relaxed tier's drift is a trajectory the next round reads,
not a boolean.

  python -m benchmarks.lowp_smoke          # prints the JSON record
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_CHILD = r"""
import json
from __graft_entry__ import _force_cpu_devices
_force_cpu_devices(8)
import jax, jax.numpy as jnp
from hadoop_tpu.models import get_config
from hadoop_tpu.parallel import MeshPlan, make_mesh
from hadoop_tpu.parallel.lowp import BITWISE_PARITY, RELAXED_PARITY
from hadoop_tpu.parallel.lowp.guard import run_loss_ab
from hadoop_tpu.parallel.train import (init_sharded, make_data_sharding,
                                       make_train_step)

out = {"steps": 50}

# ---- dp2×tp2(+sp): quantized tp reduces + chunked collective matmul
rep = run_loss_ab(MeshPlan(dp=2, tp=2, megatron_sp=True), steps=50)
out["dp2xtp2"] = {k: rep[k] for k in
                  ("accepted", "max_rel_div", "mean_rel_div",
                   "final_rel_div", "relaxed_first", "relaxed_final",
                   "bitwise_final", "comm", "codec") if k in rep}
out["dp2xtp2"]["losses_relaxed"] = rep.get("relaxed_losses")
out["dp2xtp2"]["losses_bitwise"] = rep.get("bitwise_losses")
assert rep.get("accepted"), f"dp2xtp2 guard rejected: {rep.get('reason')}"
# the partial-sync rungs below A-B the SAME plan/steps/seed — reuse
# this rung's bitwise twin instead of re-training it twice more
bit_tp = rep.get("bitwise_losses")

# ---- zero1 dp8: quantized ZeRO-1 reassembly, ≥2× payload contract
rep = run_loss_ab(MeshPlan(dp=8), zero1=True, steps=50)
out["zero1_dp8"] = {k: rep[k] for k in
                    ("accepted", "max_rel_div", "final_rel_div",
                     "relaxed_final", "bitwise_final", "comm") if k in rep}
assert rep.get("accepted"), f"zero1 guard rejected: {rep.get('reason')}"
ratio = rep["comm"].get("ratio")
assert ratio is not None and ratio >= 2.0, \
    f"zero1 quantized payload reduction {ratio} < 2x"

# ---- dp2×pp2: quantized gradient buckets on the manual schedule
rep = run_loss_ab(MeshPlan(dp=2, pp=2), steps=12, n_microbatches=2)
out["dp2xpp2"] = {k: rep[k] for k in
                  ("accepted", "max_rel_div", "final_rel_div",
                   "relaxed_final", "bitwise_final", "comm") if k in rep}
assert rep.get("accepted"), f"pp guard rejected: {rep.get('reason')}"
ratio = rep["comm"].get("ratio")
assert ratio is not None and ratio >= 2.0, \
    f"grad-bucket quantized payload reduction {ratio} < 2x"

# ---- partial-sync schedule (syncpolicy.py): periodic:2 on dp2×tp2+sp
from hadoop_tpu.parallel.lowp import ParityConfig


def _tp_site_execs(comm):
    return sum(v["executions"] for s, v in comm.get("per_site", {}).items()
               if s in ("tp.psum", "tp.scatter"))


full_execs = _tp_site_execs(out["dp2xtp2"]["comm"])
rep = run_loss_ab(MeshPlan(dp=2, tp=2, megatron_sp=True), steps=50,
                  bitwise_losses=bit_tp,
                  parity=ParityConfig(tier="relaxed",
                                      relaxed_sync="periodic:2"))
sync_execs = _tp_site_execs(rep["comm"])
exec_ratio = full_execs / max(sync_execs, 1)
out["partial_sync"] = {
    "schedule": "periodic:2", "mode": "skip",
    "guard_accepted": int(bool(rep.get("accepted"))),
    "max_rel_div": rep.get("max_rel_div"),
    "relaxed_final": rep.get("relaxed_final"),
    "tp_execs_full_per_step": full_execs,
    "tp_execs_sync_per_step": sync_execs,
    "skipped_per_step": full_execs - sync_execs,
    "exec_ratio": round(exec_ratio, 3),
    "comm": rep.get("comm")}
assert rep.get("accepted"), \
    f"partial-sync guard rejected: {rep.get('reason')}"
assert exec_ratio >= 1.8, \
    f"periodic:2 cut tp collective executions only {exec_ratio}x " \
    f"(full={full_execs}/step sync={sync_execs}/step)"
# falsifiability: a schedule that skips EVERY sync must reject — if it
# does not, the guard is not measuring anything
rep_none = run_loss_ab(MeshPlan(dp=2, tp=2, megatron_sp=True), steps=50,
                       bitwise_losses=bit_tp,
                       parity=ParityConfig(tier="relaxed",
                                           relaxed_sync="none"))
out["partial_sync"]["none_rejected"] = int(not rep_none.get("accepted"))
out["partial_sync"]["none_reason"] = rep_none.get("reason")
assert not rep_none.get("accepted"), \
    "all-layers-skipped schedule was ACCEPTED — the falsifiability " \
    "arm failed, the guard cannot be trusted"

# ---- the bitwise tier is byte-identical to parity-unset
cfg = get_config("tiny")
plan = MeshPlan(dp=2, tp=2, megatron_sp=True)
mesh = make_mesh(plan)
ds = make_data_sharding(mesh)
tokens = jax.device_put(
    jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0,
                       cfg.vocab_size, dtype=jnp.int32), ds)
targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)
losses = {}
for label, par in (("unset", None), ("bitwise", BITWISE_PARITY)):
    step = make_train_step(cfg, plan, mesh, donate=False, parity=par)
    params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan, mesh)
    ls = []
    for _ in range(3):
        params, opt, m = step(params, opt, tokens, targets)
        ls.append(float(m["loss"]))
    losses[label] = ls
assert losses["unset"] == losses["bitwise"], \
    f"BITWISE parity is not byte-identical: {losses}"
out["bitwise_bit_identical"] = True
print("LOWP_SMOKE " + json.dumps(out))
"""


def run(timeout_s: float = 900.0) -> dict:
    """The relaxed-rung record, raising on failure (run_all wraps)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the child sets its own device count
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD], cwd=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=timeout_s, env=env)
    for line in proc.stdout.splitlines():
        if line.startswith("LOWP_SMOKE "):
            return json.loads(line[len("LOWP_SMOKE "):])
    raise RuntimeError(
        f"lowp smoke produced no record (rc={proc.returncode}): "
        f"{proc.stderr.strip()[-2000:]}")


def main() -> None:
    print(json.dumps(run()))


if __name__ == "__main__":
    main()
