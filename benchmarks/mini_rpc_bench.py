"""Connection-setup latency: SIMPLE vs SASL vs TOKEN handshakes.

Counterpart of the reference's MiniRPCBenchmark (ref: hadoop-common
src/test .../ipc/MiniRPCBenchmark.java — it measures connection setup
including Kerberos/token negotiation, the cost that dominates short-
lived clients): each sample dials a FRESH connection, performs the
full handshake for its auth mode, executes one trivial call, and
closes.

  python -m benchmarks.mini_rpc_bench [--samples 50]
"""

from __future__ import annotations

import argparse
import json
import time


class _Echo:
    def echo(self, x):
        return x


def _sample(conf_srv, conf_cli, user=None, token_kind=None, samples=30):
    from hadoop_tpu.ipc import Client, Server, get_proxy
    from hadoop_tpu.security.ugi import SecretManager

    sm = SecretManager(kind=token_kind) if token_kind else None
    srv = Server(conf_srv, num_handlers=2, name="minirpc",
                 secret_manager=sm)
    srv.register_protocol("Echo", _Echo())
    srv.start()
    lat = []
    try:
        ugi = user
        if token_kind and ugi is not None:
            ugi.add_token(sm.create_token(ugi.user_name))
        for i in range(samples):
            c = Client(conf_cli, token_kind=token_kind)
            t0 = time.perf_counter()
            proxy = get_proxy("Echo", ("127.0.0.1", srv.port), client=c,
                              user=ugi)
            assert proxy.echo(i) == i
            lat.append(time.perf_counter() - t0)
            c.stop()
    finally:
        srv.stop()
    lat.sort()
    return {"p50_ms": round(lat[len(lat) // 2] * 1000, 2),
            "p95_ms": round(lat[int(len(lat) * 0.95) - 1] * 1000, 2),
            "samples": samples}


def run(samples: int = 30) -> dict:
    import tempfile

    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.security.ugi import UserGroupInformation
    from hadoop_tpu.testing.minikdc import MiniKdc

    out = {}
    simple = Configuration(load_defaults=False)
    out["simple"] = _sample(simple, simple, samples=samples)

    with tempfile.TemporaryDirectory() as td:
        kdc = MiniKdc(td)
        kdc.create_principal("bench", b"bench-pw")
        server_keytab = kdc.create_keytab(f"{td}/server.keytab")
        for qop in ("authentication", "privacy"):
            conf = Configuration(load_defaults=False)
            conf.set("hadoop.security.authentication", "sasl")
            conf.set("hadoop.rpc.protection", qop)
            conf.set("hadoop.security.server.keytab", server_keytab)
            ugi = UserGroupInformation.login_from_keytab(
                "bench", kdc.keytab_for("bench"))
            out[f"sasl_{qop}"] = _sample(conf, conf, user=ugi,
                                         samples=samples)

    # TOKEN auth (the job-token shape: secret manager on the server)
    tok_conf = Configuration(load_defaults=False)
    ugi = UserGroupInformation.create_remote_user("bench")
    out["token"] = _sample(tok_conf, tok_conf, user=ugi,
                           token_kind="bench-token", samples=samples)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=30)
    args = ap.parse_args()
    print(json.dumps(run(args.samples)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
