"""Multi-process RPC throughput: N SO_REUSEPORT workers vs one process.

  python -m benchmarks.mprpc_bench [--seconds 5] [--workers 4]
"""

from __future__ import annotations

import argparse
import json
import threading
import time


def bench_factory(conf):
    """Stateless echo protocols for every worker (module-level so forked
    children can import it by path)."""
    class BenchProtocol:
        def ping(self, n):
            return n

        def payload(self, blob):
            return len(blob)
    return {"BenchProtocol": BenchProtocol()}


def run(seconds: float = 5.0, client_threads: int = 16,
        workers: int = 4, handlers: int = 4) -> dict:
    from hadoop_tpu.ipc import Client, get_proxy
    from hadoop_tpu.ipc.mpserver import MultiProcessServer

    srv = MultiProcessServer(
        factory="benchmarks.mprpc_bench:bench_factory",
        num_workers=workers, num_handlers=handlers, name="mpbench")
    srv.start()
    stop = threading.Event()
    counts = [0] * client_threads
    clients = [Client() for _ in range(client_threads)]

    def worker(idx: int) -> None:
        proxy = get_proxy("BenchProtocol", ("127.0.0.1", srv.port),
                          client=clients[idx])
        n = 0
        while not stop.is_set():
            proxy.ping(n)
            n += 1
        counts[idx] = n

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(client_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    dt = time.perf_counter() - t0
    total = sum(counts)
    for c in clients:
        c.stop()
    alive = srv.alive_workers()
    srv.stop()
    return {"calls_per_sec": round(total / dt, 1), "total_calls": total,
            "client_threads": client_threads, "workers": workers,
            "handlers_per_worker": handlers, "workers_alive": alive}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--clients", type=int, default=16)
    args = ap.parse_args()
    print(json.dumps(run(args.seconds, args.clients, args.workers)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
