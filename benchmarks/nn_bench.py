"""NNBench: NameNode metadata latency/TPS under real MR load.

Counterpart of the reference's NNBench (ref: hadoop-mapreduce-client-
jobclient/.../hdfs/NNBench.java — metadata ops driven FROM MAP TASKS so
the NN is measured under the cluster's own task-launch + heartbeat +
shuffle-control load, unlike NNThroughputBenchmark's in-process drive).

  python -m benchmarks.nn_bench [--maps 4] [--ops 200]
"""

from __future__ import annotations

import argparse
import json
import time


def run(maps: int = 4, ops_per_map: int = 200) -> dict:
    import statistics

    from hadoop_tpu.mapreduce import Job
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from benchmarks import bench_base_dir

    base = bench_base_dir("nnbench")
    with MiniMRYarnCluster(num_nodes=2, base_dir=base) as cluster:
        fs = cluster.get_filesystem()
        fs.mkdirs("/nnbench-in")
        fs.write_all("/nnbench-in/seed", b"x")
        # explicit dotted refs: under `python -m`, class_ref would say
        # __main__ and containers could not import that
        job = (Job(cluster.rm_addr, cluster.default_fs, name="nnbench")
               .set_mapper("benchmarks.nn_bench:_NNBenchMapper")
               .add_input_path("/nnbench-in")
               .set_output_path("/nnbench-out")
               .set_num_reduces(0)
               .set("nnbench.ops", str(ops_per_map))
               .set("nnbench.fs", cluster.default_fs)
               .set("nnbench.maps", str(maps)))
        job.set_input_format("benchmarks.nn_bench:_NSplits") \
           .set(_NSplits.NUM_MAPS_KEY, str(maps))
        t0 = time.perf_counter()
        ok = job.wait_for_completion(timeout=300)
        wall = time.perf_counter() - t0
        if not ok:
            return {"error": "nnbench job failed",
                    "diagnostics": job.diagnostics[:3]}
        # every map emits its op latencies (ms) as output records
        lats = []
        for st in fs.list_status("/nnbench-out"):
            if "part-m-" not in st.path:
                continue
            for line in fs.read_all(st.path).decode().splitlines():
                _, _, val = line.partition("\t")
                if val:
                    lats.extend(float(x) for x in val.split(",") if x)
        lats.sort()
        total_ops = maps * ops_per_map * 4  # create+write, stat, rename, del
        return {
            "maps": maps, "ops_per_map_cycle": ops_per_map,
            "total_metadata_ops": total_ops,
            "ops_per_sec": round(total_ops / wall, 1),
            "op_latency_ms": {
                "p50": round(statistics.median(lats), 2) if lats else None,
                "p95": round(lats[int(len(lats) * 0.95) - 1], 2)
                if lats else None,
            },
            "wall_seconds": round(wall, 2),
        }


from hadoop_tpu.mapreduce.api import InputFormat, Mapper


class _NSplits(InputFormat):
    NUM_MAPS_KEY = "nnbench.splits"

    def get_splits(self, fs, paths, conf):
        from hadoop_tpu.mapreduce.api import FileSplit
        n = int(conf.get(self.NUM_MAPS_KEY, "1"))
        return [FileSplit(f"synthetic://nnbench/{i}", 0, 1)
                for i in range(n)]

    def read(self, fs, split, conf):
        yield split.path.encode(), b""


class _NNBenchMapper(Mapper):
    def map(self, key, value, ctx):
        import time as _time

        from hadoop_tpu.fs import FileSystem
        fs = FileSystem.get(ctx.conf.get("nnbench.fs"))
        me = key.decode().rsplit("/", 1)[-1]
        n = int(ctx.conf.get("nnbench.ops", "100"))
        lats = []
        root = f"/nnbench-work/{me}"
        fs.mkdirs(root)
        for i in range(n):
            p = f"{root}/f{i}"
            t0 = _time.perf_counter()
            fs.write_all(p, b"d")             # create+write+complete
            lats.append(_time.perf_counter() - t0)
            t0 = _time.perf_counter()
            fs.get_file_status(p)             # stat
            lats.append(_time.perf_counter() - t0)
            t0 = _time.perf_counter()
            fs.rename(p, p + ".r")            # rename
            lats.append(_time.perf_counter() - t0)
            t0 = _time.perf_counter()
            fs.delete(p + ".r")               # delete
            lats.append(_time.perf_counter() - t0)
        fs.close()
        ctx.emit(me.encode(),
                 ",".join(f"{x * 1000:.3f}" for x in lats).encode())


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--maps", type=int, default=4)
    ap.add_argument("--ops", type=int, default=200)
    args = ap.parse_args()
    print(json.dumps(run(args.maps, args.ops)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
