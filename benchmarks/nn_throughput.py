"""NameNode metadata throughput: ops/sec per op type.

Counterpart of the reference's NNThroughputBenchmark (ref: hadoop-hdfs
src/test/java/.../server/namenode/NNThroughputBenchmark.java): drives the
NameNode's RPC-facing protocol object IN-PROCESS (no network) with many
client threads, so the number measured is the namesystem's own op rate —
lock discipline, edit-log group commit, and retry-cache included.

  python -m benchmarks.nn_throughput [--ops 5000] [--threads 16]
"""

from __future__ import annotations

import argparse
import json
import shutil
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor


def _rate(fn, n_ops: int, threads: int) -> float:
    """Run fn(i) for i in range(n_ops) across threads; return ops/sec."""
    pool = ThreadPoolExecutor(max_workers=threads)
    t0 = time.perf_counter()
    list(pool.map(fn, range(n_ops), chunksize=max(1, n_ops // threads // 4)))
    dt = time.perf_counter() - t0
    pool.shutdown()
    return n_ops / dt


def run(n_ops: int = 5000, threads: int = 16) -> dict:
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.dfs.namenode.namenode import ClientProtocol, NameNode
    from hadoop_tpu.testing.minicluster import fast_conf

    base = tempfile.mkdtemp(prefix="htpu-nnbench-")
    conf = fast_conf()
    conf.set("dfs.namenode.safemode.threshold-pct", "0")
    nn = NameNode(Configuration(other=conf), name_dir=base + "/name")
    nn.init(conf)
    nn.start()
    proto = ClientProtocol(nn.fsn, nn.retry_cache)
    results = {}
    try:
        results["mkdirs"] = _rate(
            lambda i: proto.mkdirs(f"/bench/dirs/{i % 100}/{i}"),
            n_ops, threads)
        def create(i):
            p = f"/bench/files/{i % 100}/f{i}"
            proto.create(p, client_name=f"bench-{i % threads}")
            proto.complete(p, client_name=f"bench-{i % threads}")
        results["create"] = _rate(create, n_ops, threads)
        results["open"] = _rate(
            lambda i: proto.get_block_locations(
                f"/bench/files/{i % 100}/f{i}"), n_ops, threads)
        results["fileinfo"] = _rate(
            lambda i: proto.get_file_info(f"/bench/files/{i % 100}/f{i}"),
            n_ops, threads)
        results["rename"] = _rate(
            lambda i: proto.rename(f"/bench/files/{i % 100}/f{i}",
                                   f"/bench/files/{i % 100}/r{i}"),
            n_ops, threads)
        results["delete"] = _rate(
            lambda i: proto.delete(f"/bench/files/{i % 100}/r{i}"),
            n_ops, threads)
    finally:
        nn.stop()
        shutil.rmtree(base, ignore_errors=True)
    return {k: round(v, 1) for k, v in results.items()}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=5000)
    ap.add_argument("--threads", type=int, default=16)
    args = ap.parse_args()
    ops = run(args.ops, args.threads)
    print(json.dumps({
        "metric": "nn_throughput_ops_per_sec", "value": ops["create"],
        "unit": "create ops/s", "per_op": ops,
        "n_ops": args.ops, "threads": args.threads,
    }))


if __name__ == "__main__":
    main()
