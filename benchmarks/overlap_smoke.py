"""CPU-mesh overlap smoke: A-B step parity + async-save blocking time.

Runs (in a SUBPROCESS, so the 8-virtual-device XLA flags are set before
jax initializes — same trick as the multichip dryrun) a dp2×tp2 train
step with the communication-overlap pass on and off and asserts the
losses are bit-identical, then measures how long a checkpoint blocks
the caller sync vs async. Mirrors the serving smoke's contract in
run_all.py: a failure is recorded as data, never a reason to lose the
other benches.

  python -m benchmarks.overlap_smoke          # prints the JSON record
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_CHILD = r"""
import json, time
from __graft_entry__ import _force_cpu_devices
_force_cpu_devices(8)
import jax, jax.numpy as jnp
from hadoop_tpu.models import get_config
from hadoop_tpu.parallel import MeshPlan, make_mesh
from hadoop_tpu.parallel.overlap import DEFAULT_OVERLAP, OVERLAP_OFF
from hadoop_tpu.parallel.train import (init_sharded, make_data_sharding,
                                       make_train_step)

cfg = get_config("tiny")
plan = MeshPlan(dp=2, tp=2, megatron_sp=True)
mesh = make_mesh(plan)
ds = make_data_sharding(mesh)
tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0,
                            cfg.vocab_size, dtype=jnp.int32)
tokens = jax.device_put(tokens, ds)
targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)
N_STEPS = 3
out = {"plan": "dp2xtp2+sp", "steps": N_STEPS}
losses = {}
for label, ov in (("on", DEFAULT_OVERLAP), ("off", OVERLAP_OFF)):
    step = make_train_step(cfg, plan, mesh, donate=False, overlap=ov)
    params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan, mesh)
    ls, t0 = [], time.perf_counter()
    for _ in range(N_STEPS):
        params, opt, m = step(params, opt, tokens, targets)
        ls.append(float(m["loss"]))
    out[f"wall_s_{label}"] = round(time.perf_counter() - t0, 3)
    losses[label] = ls
out["losses"] = losses["on"]
assert losses["on"] == losses["off"], \
    f"overlap parity broken: on={losses['on']} off={losses['off']}"
out["parity"] = "bit-exact"

# async-save blocking time on the same state
import tempfile, shutil
from hadoop_tpu.fs import FileSystem
from hadoop_tpu.parallel.checkpoint import (AsyncCheckpointWriter,
                                            snapshot_tree, write_snapshot)
td = tempfile.mkdtemp(prefix="overlap-smoke-")
try:
    fs = FileSystem.get(f"file://{td}")
    t0 = time.perf_counter()
    snap = snapshot_tree({"params": params, "opt": opt})
    out["ckpt_blocking_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
    w = AsyncCheckpointWriter()
    t0 = time.perf_counter()
    w.submit(lambda: write_snapshot(fs, f"{td}/c", 1, snap))
    w.wait()
    out["ckpt_write_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
finally:
    shutil.rmtree(td, ignore_errors=True)
print("OVERLAP_SMOKE " + json.dumps(out))
"""


def run(timeout_s: float = 600.0) -> dict:
    """The A-B parity + ckpt record, raising on failure (run_all wraps)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the child sets its own device count
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD], cwd=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=timeout_s, env=env)
    for line in proc.stdout.splitlines():
        if line.startswith("OVERLAP_SMOKE "):
            return json.loads(line[len("OVERLAP_SMOKE "):])
    raise RuntimeError(
        f"overlap smoke produced no record (rc={proc.returncode}): "
        f"{proc.stderr.strip()[-2000:]}")


def main() -> None:
    print(json.dumps(run()))


if __name__ == "__main__":
    main()
