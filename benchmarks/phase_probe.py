"""Phase-timing probe for the tunneled TPU backend.

Prints a wall-clock mark after every phase of one tiny train step so a
hung or slow phase is attributable (the bench ladder only reports
whole-rung budgets). Writes to stdout unbuffered; run as
``python -u -m benchmarks.phase_probe [preset]``.
"""

import os
import sys
import time

t0 = time.time()


def mark(s):
    print(f"{time.time() - t0:8.1f}s  {s}", flush=True)


def main():
    preset = sys.argv[1] if len(sys.argv) > 1 else "tiny"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    remat = sys.argv[3] if len(sys.argv) > 3 else "none"
    remat_arg = {"none": False, "full": True, "dots": "dots"}[remat]
    import jax
    import jax.numpy as jnp
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(here, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    mark(f"jax imported, devices={jax.devices()}")
    from hadoop_tpu.models import count_params, get_config
    from hadoop_tpu.parallel import MeshPlan, make_mesh
    from hadoop_tpu.parallel.train import (init_sharded, make_data_sharding,
                                           make_train_step)
    mark("framework imported")
    cfg = get_config(preset, max_seq=2048)
    plan = MeshPlan()
    mesh = make_mesh(plan)
    step = make_train_step(cfg, plan, mesh, remat=remat_arg, donate=True)
    mark("train step built")
    params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan, mesh)
    mark("init traced/dispatched")
    jax.block_until_ready(params)
    mark(f"init done, params={count_params(params)}")
    ds = make_data_sharding(mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (batch, 2048), 0,
                           cfg.vocab_size, dtype=jnp.int32), ds)
    targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)
    jax.block_until_ready((tokens, targets))
    mark("data ready")
    lowered = step.lower(params, opt, tokens, targets)
    mark("lowered")
    compiled = lowered.compile()
    mark("compiled")
    params, opt, metrics = compiled(params, opt, tokens, targets)
    mark("step 1 dispatched")
    loss = float(metrics["loss"])
    mark(f"step 1 synced (loss={loss:.4f})")
    t1 = time.time()
    for _ in range(5):
        params, opt, metrics = compiled(params, opt, tokens, targets)
    float(metrics["loss"])
    dt = time.time() - t1
    mark(f"5 steps in {dt:.2f}s = {batch * 2048 * 5 / dt:.0f} tok/s")


if __name__ == "__main__":
    main()
