"""Component-level timing of the flagship train step (diagnosis tool).

Times forward-only, fwd+bwd, and the full optimizer step separately at
several batch sizes to locate super-linear scaling.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp

from hadoop_tpu.models import count_params, get_config
from hadoop_tpu.parallel import MeshPlan, make_mesh
from hadoop_tpu.parallel.train import (init_sharded, make_data_sharding,
                                       make_train_step)


def timeit(fn, *args, steps=8, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.tree_util.tree_map(
        lambda x: x.block_until_ready() if hasattr(x, "block_until_ready")
        else x, out)
    # sync via host transfer (axon block_until_ready returns early)
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))
    return (time.perf_counter() - t0) / steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="flagship-420m")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batches", default="4,8,16")
    ap.add_argument("--remat", default="full",
                    choices=["none", "full", "dots"])
    args = ap.parse_args()
    remat = {"none": False, "full": True, "dots": "dots"}[args.remat]

    cfg = get_config(args.preset, max_seq=args.seq)
    plan = MeshPlan()
    mesh = make_mesh(plan)
    params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan, mesh)
    ds = make_data_sharding(mesh)

    from hadoop_tpu.models.decoder import forward_hidden
    for batch in [int(x) for x in args.batches.split(",")]:
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (batch, args.seq), 0,
                               cfg.vocab_size, dtype=jnp.int32), ds)
        targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)

        from hadoop_tpu.models.config import ModelConfig
        from hadoop_tpu.parallel.train import _loss_from_h
        ctx = plan.ctx(cfg)

        @jax.jit
        def fwd_only(params, tokens, targets):
            h = forward_hidden(params, tokens, cfg, ctx, remat=remat)
            return _loss_from_h(params, h, targets, cfg, ctx)

        @jax.jit
        def fwd_bwd(params, tokens, targets):
            def f(p):
                h = forward_hidden(p, tokens, cfg, ctx, remat=remat)
                return _loss_from_h(p, h, targets, cfg, ctx)
            return jax.value_and_grad(f)(params)

        step = make_train_step(cfg, plan, mesh, remat=remat, donate=False)

        t_f = timeit(fwd_only, params, tokens, targets)
        t_fb = timeit(fwd_bwd, params, tokens, targets)
        t_full = timeit(step, params, opt, tokens, targets)
        tok = batch * args.seq
        print(f"batch={batch:3d} fwd={t_f*1e3:8.1f}ms "
              f"fwd+bwd={t_fb*1e3:8.1f}ms full={t_full*1e3:8.1f}ms "
              f"tok/s(full)={tok/t_full:,.0f}")


if __name__ == "__main__":
    main()
