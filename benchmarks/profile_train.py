"""Component-level timing of the flagship train step (diagnosis tool).

Times forward-only, fwd+bwd, and the full optimizer step separately at
several batch sizes to locate super-linear scaling, and — the overlap
round's additions — times the step with the communication-overlap pass
on vs off (``--overlap both``) and breaks out checkpointing into its
blocking (host-snapshot) and background (DFS write) halves
(``--ckpt both``). On a single-device plan the A-B delta is compile
noise by construction (the pass only changes collectives); on a
multichip plan it is the recovered communication time.

``--parity both`` adds the relaxed-tier rung (parallel/lowp):
quantized collective payloads + the true chunked collective matmul,
timed beside the bitwise step, with the trace-time comm-byte ledger
(payload vs reference bytes per step) in the row — so every future
run of the ladder prices BOTH tiers. ``--guard-steps N`` additionally
runs the loss-curve A-B acceptance over N training steps and records
the verdict in the JSON.

  python -m benchmarks.profile_train --preset tiny --seq 512 \
      --dp 2 --tp 2 --overlap both --ckpt both --parity both
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time

import jax
import jax.numpy as jnp

from hadoop_tpu.models import count_params, get_config
from hadoop_tpu.parallel import MeshPlan, make_mesh
from hadoop_tpu.parallel.overlap import DEFAULT_OVERLAP, OVERLAP_OFF
from hadoop_tpu.parallel.train import (init_sharded, make_data_sharding,
                                       make_train_step)


def timeit(fn, *args, steps=8, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.tree_util.tree_map(
        lambda x: x.block_until_ready() if hasattr(x, "block_until_ready")
        else x, out)
    # sync via host transfer (axon block_until_ready returns early)
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))
    return (time.perf_counter() - t0) / steps


def ckpt_breakdown(params, opt, mode: str) -> dict:
    """Blocking vs background checkpoint cost on a local FileSystem.

    sync_ms: the whole old-style save (what the step loop used to eat).
    snapshot_ms: the device→host copy — ALL an async save blocks for.
    write_ms: the DFS write the background thread absorbs.
    """
    import shutil
    import tempfile

    from hadoop_tpu.fs import FileSystem
    from hadoop_tpu.parallel.checkpoint import (AsyncCheckpointWriter,
                                                save_checkpoint,
                                                snapshot_tree,
                                                write_snapshot)
    out: dict = {}
    td = tempfile.mkdtemp(prefix="profile-ckpt-")
    try:
        fs = FileSystem.get(f"file://{td}")
        tree = {"params": params, "opt": opt}
        if mode in ("sync", "both"):
            t0 = time.perf_counter()
            save_checkpoint(fs, f"{td}/sync", 1, tree)
            out["sync_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        if mode in ("async", "both"):
            t0 = time.perf_counter()
            snap = snapshot_tree(tree)
            t1 = time.perf_counter()
            out["snapshot_ms"] = round((t1 - t0) * 1e3, 2)
            writer = AsyncCheckpointWriter()
            writer.submit(lambda: write_snapshot(fs, f"{td}/async", 1,
                                                 snap))
            writer.wait()
            out["write_ms"] = round((time.perf_counter() - t1) * 1e3, 2)
    finally:
        shutil.rmtree(td, ignore_errors=True)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="flagship-420m")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batches", default="4,8,16")
    ap.add_argument("--remat", default="full",
                    choices=["none", "full", "dots"])
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--overlap", default="on",
                    choices=["on", "off", "both"],
                    help="communication-overlap pass A-B mode")
    ap.add_argument("--parity", default="bitwise",
                    choices=["bitwise", "relaxed", "both"],
                    help="parity tier rungs (parallel/lowp)")
    ap.add_argument("--sync-schedule", default="full",
                    help="relaxed-tier TP activation-sync schedule "
                         "(parallel.lowp.sync.schedule: full | none | "
                         "periodic:<k> | layers:<spec>) — priced on "
                         "the relaxed rung and recorded in its policy "
                         "dict + comm ledger")
    ap.add_argument("--sync-mode", default="skip",
                    choices=["skip", "stale"],
                    help="what a scheduled-off layer does "
                         "(parallel.lowp.sync.mode)")
    ap.add_argument("--guard-steps", type=int, default=0,
                    help="also run the relaxed loss-curve A-B guard "
                         "over this many steps (0 = skip)")
    ap.add_argument("--ckpt", default="none",
                    choices=["none", "sync", "async", "both"],
                    help="include a checkpoint blocking-time breakdown")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object instead of per-line text")
    args = ap.parse_args()
    remat = {"none": False, "full": True, "dots": "dots"}[args.remat]

    cfg = get_config(args.preset, max_seq=args.seq)
    plan = MeshPlan(dp=args.dp, tp=args.tp, pp=args.pp,
                    megatron_sp=args.tp > 1)
    mesh = make_mesh(plan)
    params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan, mesh)
    ds = make_data_sharding(mesh)
    overlaps = {"on": [("overlap-on", DEFAULT_OVERLAP)],
                "off": [("overlap-off", OVERLAP_OFF)],
                "both": [("overlap-on", DEFAULT_OVERLAP),
                         ("overlap-off", OVERLAP_OFF)]}[args.overlap]

    from hadoop_tpu.models.decoder import forward_hidden
    from hadoop_tpu.parallel.train import _loss_from_h
    report: dict = {"preset": args.preset, "seq": args.seq,
                    "plan": {"dp": args.dp, "tp": args.tp, "pp": args.pp},
                    "remat": args.remat, "params": count_params(params),
                    "parity_mode": args.parity,
                    "batches": []}
    for batch in [int(x) for x in args.batches.split(",")]:
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (batch, args.seq), 0,
                               cfg.vocab_size, dtype=jnp.int32), ds)
        targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)

        ctx = plan.ctx(cfg)

        @jax.jit
        def fwd_only(params, tokens, targets):
            h = forward_hidden(params, tokens, cfg, ctx, remat=remat)
            return _loss_from_h(params, h, targets, cfg, ctx)

        @jax.jit
        def fwd_bwd(params, tokens, targets):
            def f(p):
                h = forward_hidden(p, tokens, cfg, ctx, remat=remat)
                return _loss_from_h(p, h, targets, cfg, ctx)
            return jax.value_and_grad(f)(params)

        from hadoop_tpu.parallel.lowp import (BITWISE_PARITY,
                                              ParityConfig)
        from hadoop_tpu.parallel.lowp.quant import capture_comm
        relaxed_par = ParityConfig(
            tier="relaxed", relaxed_sync=args.sync_schedule,
            relaxed_sync_mode=args.sync_mode)
        parities = {"bitwise": [("", BITWISE_PARITY)],
                    "relaxed": [("parity-relaxed_", relaxed_par)],
                    "both": [("", BITWISE_PARITY),
                             ("parity-relaxed_", relaxed_par)]}[
            args.parity]
        row: dict = {"batch": batch}
        # single-trace components are only meaningful single-device (no
        # collectives outside shard_map); skip them on multichip plans
        if plan.n_devices == 1:
            row["fwd_ms"] = round(
                timeit(fwd_only, params, tokens, targets) * 1e3, 1)
            t_fb = timeit(fwd_bwd, params, tokens, targets)
            row["bwd_ms"] = round(t_fb * 1e3 - row["fwd_ms"], 1)
        for plabel, par in parities:
            for olabel, ov in overlaps:
                if par.relaxed and not ov.enabled:
                    # relaxed rides the overlap pass's collectives;
                    # make_train_step refuses the combination
                    continue
                label = plabel + olabel
                try:
                    with capture_comm() as ledger:
                        step = make_train_step(cfg, plan, mesh,
                                               remat=remat,
                                               donate=False, overlap=ov,
                                               parity=par)
                        t_full = timeit(step, params, opt, tokens,
                                        targets)
                except Exception as e:  # noqa: BLE001 — a step that
                    # cannot run on this backend (e.g. no vma tracking)
                    # is a data point; the fwd/bwd and ckpt numbers
                    # must still land
                    row[label + "_error"] = f"{type(e).__name__}"
                    continue
                row[label + "_ms"] = round(t_full * 1e3, 1)
                row[label + "_tok_s"] = round(batch * args.seq / t_full)
                if par.relaxed and ledger.sites:
                    row[label + "_comm"] = ledger.report()
                    # the policy that produced this row, next to its
                    # ledger — bench rows stay self-describing when
                    # tiers multiply (codec/group/consumer flags here,
                    # the serving weight plane in serve_bench's JSON)
                    row[label + "_policy"] = dataclasses.asdict(par)
        if "fwd_ms" in row and "overlap-on_ms" in row:
            # optimizer + (unoverlapped) comm residue: what the full
            # step spends beyond fwd+bwd compute
            row["opt_comm_ms"] = round(
                row["overlap-on_ms"] - row["fwd_ms"] - row["bwd_ms"], 1)
        if "overlap-on_ms" in row and "overlap-off_ms" in row:
            row["overlap_gain_ms"] = round(
                row["overlap-off_ms"] - row["overlap-on_ms"], 1)
        report["batches"].append(row)
        if not args.json:
            print(" ".join(f"{k}={v}" for k, v in row.items()))

    if args.guard_steps > 0:
        # loss-curve A-B acceptance (parallel/lowp/guard.py): the
        # relaxed trajectory must stay within the bounded divergence
        # of its bitwise twin. Recorded verbatim in the JSON.
        from hadoop_tpu.parallel.lowp import ParityConfig
        from hadoop_tpu.parallel.lowp.guard import run_loss_ab
        try:
            report["parity_guard"] = run_loss_ab(
                plan, preset=args.preset, steps=args.guard_steps,
                seq=min(args.seq, 128),
                parity=ParityConfig(tier="relaxed",
                                    relaxed_sync=args.sync_schedule,
                                    relaxed_sync_mode=args.sync_mode))
        except Exception as e:  # noqa: BLE001 — a backend that cannot
            # run the step records the gap instead of dying
            report["parity_guard"] = {"error": f"{type(e).__name__}"}
        if not args.json:
            pg = report["parity_guard"]
            print("parity_guard " + " ".join(
                f"{k}={pg[k]}" for k in ("accepted", "max_rel_div",
                                         "final_rel_div", "reason")
                if k in pg))

    if args.ckpt != "none":
        report["ckpt"] = ckpt_breakdown(params, opt, args.ckpt)
        if not args.json:
            print("ckpt " + " ".join(
                f"{k}={v}" for k, v in report["ckpt"].items()))
    if args.json:
        print(json.dumps(report))


if __name__ == "__main__":
    main()
