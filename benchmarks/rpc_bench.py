"""RPC calls/sec: client/server thread scaling over the real wire path.

Counterpart of the reference's RPCCallBenchmark (ref: hadoop-common
src/test/java/org/apache/hadoop/ipc/RPCCallBenchmark.java): a server with
H handlers, C client threads each hammering a trivial echo method over
real TCP connections — measures the Listener→Reader→CallQueue→Handler→
Responder reactor end to end.

  python -m benchmarks.rpc_bench [--seconds 5] [--client-threads 8]
"""

from __future__ import annotations

import argparse
import json
import threading
import time


class BenchProtocol:
    def ping(self, x: int) -> int:
        return x + 1

    def payload(self, data: bytes) -> int:
        return len(data)


def run(seconds: float = 5.0, client_threads: int = 8,
        handlers: int = 8, payload_kb: int = 0) -> dict:
    from hadoop_tpu.ipc import Client, Server, get_proxy

    srv = Server(num_handlers=handlers, name="rpcbench")
    srv.register_protocol("BenchProtocol", BenchProtocol())
    srv.start()
    stop = threading.Event()
    counts = [0] * client_threads
    clients = [Client() for _ in range(client_threads)]
    blob = b"x" * (payload_kb * 1024)

    def worker(idx: int) -> None:
        proxy = get_proxy("BenchProtocol", ("127.0.0.1", srv.port),
                          client=clients[idx])
        n = 0
        if payload_kb:
            while not stop.is_set():
                proxy.payload(blob)
                n += 1
        else:
            while not stop.is_set():
                proxy.ping(n)
                n += 1
        counts[idx] = n

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(client_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    dt = time.perf_counter() - t0
    for c in clients:
        c.stop()
    srv.stop()
    total = sum(counts)
    return {"calls_per_sec": round(total / dt, 1), "total_calls": total,
            "client_threads": client_threads, "handlers": handlers}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--client-threads", type=int, default=8)
    ap.add_argument("--handlers", type=int, default=8)
    ap.add_argument("--payload-kb", type=int, default=0)
    args = ap.parse_args()
    r = run(args.seconds, args.client_threads, args.handlers,
            args.payload_kb)
    print(json.dumps({
        "metric": "rpc_calls_per_sec", "value": r["calls_per_sec"],
        "unit": "calls/s", **r,
    }))


if __name__ == "__main__":
    main()
