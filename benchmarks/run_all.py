"""Run every storage/compute benchmark and record STORAGE_BENCH.json.

  python -m benchmarks.run_all [--out STORAGE_BENCH.json]
"""

from __future__ import annotations

import argparse
import json
import platform
import time


def _dynamometer(n_ops: int) -> dict:
    import os
    import tempfile

    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf
    from hadoop_tpu.tools import dynamometer as dyn

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    import shutil
    base = tempfile.mkdtemp(prefix="dynamometer-",
                            dir="/dev/shm" if os.path.isdir("/dev/shm")
                            else None)
    try:
        with MiniDFSCluster(num_datanodes=1, conf=conf,
                            base_dir=base) as c:
            c.wait_active()
            trace = os.path.join(base, "audit.log")
            dyn.generate_trace(trace, n_ops, workers=8)
            with open(trace) as f:
                return dyn.replay_parallel(c.default_fs, list(f),
                                           threads=8)
    finally:
        shutil.rmtree(base, ignore_errors=True)


def _lint_selfrun() -> dict:
    """tpulint self-run as a bench suite: the full tree against the
    committed baseline plus the conf-registry drift gate — a dirty
    tree or a stale registry is a trajectory failure like any other."""
    import os

    from hadoop_tpu.analysis import all_checkers, confscan
    from hadoop_tpu.analysis.core import (load_baseline, run_lint,
                                          split_baselined)
    from hadoop_tpu.conf import registry

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    t0 = time.perf_counter()
    checkers = all_checkers()
    findings = run_lint([os.path.join(repo, "hadoop_tpu")],
                        checkers=checkers, root=repo)
    baseline = load_baseline(os.path.join(repo, "LINT_BASELINE"))
    new, old = split_baselined(findings, baseline)
    gate_ok, diff = confscan.check_registry(repo)
    failures = [f.render() for f in new[:20]]
    if not gate_ok:
        failures.append(f"conf registry stale ({len(diff)} diff lines)")
    return {"checkers": len(checkers),
            "unbaselined": len(new),
            "baselined": len(old),
            "registry_keys": len(registry.KEYS),
            "registry_patterns": len(registry.PATTERNS),
            "registry_gate_ok": gate_ok,
            "wall_seconds": round(time.perf_counter() - t0, 2),
            "failures": failures}


def _code_hash() -> str:
    """Short git hash of the tree the suite ran against (the train-row
    precedent in BENCH_LOG.jsonl carries the same ``code`` field)."""
    import os
    import subprocess
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))).stdout.strip()
    except Exception:  # noqa: BLE001 — no git = no hash, not no log
        return ""


def _suite_failures(result: dict) -> list:
    """Failure strings a suite reported, whatever its local shape:
    an ``error`` (the suite itself died) or a ``failures`` list (a
    contract inside it failed)."""
    if not isinstance(result, dict):
        return []
    out = []
    if result.get("error"):
        out.append(str(result["error"]))
    # both in-tree conventions: doctor/flight use "failures",
    # longctx/serve_bench contracts use "failed"
    for key in ("failures", "failed"):
        for f in result.get(key) or []:
            out.append(str(f))
    return out


# per-suite key metrics for the trajectory row: a list of (path into
# the suite result, logged name) per suite. Scalars only — the full
# result stays in --out.
_KEY_METRICS = {
    "nn_throughput_ops_per_sec": [(("create",), "create_ops_per_sec")],
    "dfsio": [(("write_mb_s",), "write_mb_s")],
    "terasort": [(("sort_bytes_per_sec",), "sort_bytes_per_sec")],
    "serving": [(("value",), "ttft_p50_ms")],
    "serving_speculate": [(("steps_ratio",), "steps_ratio")],
    "serving_quantized": [(("value",), "capacity_ratio")],
    # expert-parallel MoE serving (serving/engine._moe_mlp): the lever
    # counts as moving when the trajectory shows sparse tokens/s priced
    # against dense-compute NEXT TO the ledger-measured a2a byte cut
    # and the guard verdict that bought it
    "serving_moe": [
        (("moe_tokens_per_sec",), "moe_tokens_per_sec"),
        (("dense_tokens_per_sec",), "moe_dense_tokens_per_sec"),
        (("moe_a2a_payload_ratio",), "moe_a2a_payload_ratio"),
        (("guard_accepted",), "moe_guard_accepted"),
        (("falsifier_rejected",), "moe_falsifier_rejected")],
    "trace_overhead": [(("step", "overhead_frac"), "overhead_frac")],
    "doctor": [(("windows_to_flag",), "windows_to_flag")],
    "flight_recorder": [(("windows_to_flag",), "windows_to_flag")],
    # elastic training plane (parallel/elastic): the lever only counts
    # as moving when the trajectory shows the eviction taken AND fewer
    # steps lost than a restart-from-checkpoint would lose
    "flight_elastic": [(("lost_steps",), "lost_steps"),
                       (("lost_steps_baseline",), "lost_steps_baseline"),
                       (("evictions",), "evictions"),
                       (("resume_seconds",), "resume_seconds")],
    # long-context pipelined decode (serving/longctx/decode): the
    # lever counts as moving when the trajectory shows decode tokens/s
    # NEXT TO the per-token dispatch budget and the double-buffer
    # window bytes it was bought with
    "serving_longctx": [
        (("decode_tokens_per_sec",), "longctx_decode_tokens_per_sec"),
        (("decode_dispatches_per_token",),
         "longctx_dispatches_per_token"),
        (("decode_hbm_window_bytes",), "longctx_hbm_window_bytes")],
    # partially-synchronized activations (parallel/lowp/syncpolicy):
    # the lever only counts as moving when the trajectory file shows
    # per-step collectives skipped AND the guard verdict next to them
    "lowp": [(("partial_sync", "skipped_per_step"),
              "sync_skipped_per_step"),
             (("partial_sync", "exec_ratio"), "sync_exec_ratio"),
             (("partial_sync", "guard_accepted"),
              "sync_guard_accepted")],
    # elastic-fleet storm (autoscaler + QoS door + SLO scoreboard):
    # the trajectory shows how far the fleet grew, that zero requests
    # failed, and that the DFS tier recovered after the drain
    "serving_storm": [(("value",), "peak_replicas"),
                      (("failed_requests",), "storm_failed_requests"),
                      (("hits_dfs_delta",), "storm_hits_dfs_delta"),
                      (("qos_heavy_sheds",), "storm_heavy_sheds")],
    # static-analysis plane: the self-run is healthy when it stays at
    # zero unbaselined findings with the registry gate green
    "lint": [(("unbaselined",), "unbaselined"),
             (("registry_keys",), "registry_keys"),
             (("wall_seconds",), "wall_seconds")],
}


def _bench_row(out: dict, quick: bool) -> dict:
    """The ``bench_suite`` trajectory row for one full run — built
    separately from the append so the trend sentinel can judge the
    row BEFORE it lands in the log."""
    summary = {}
    failures = []
    for suite, result in out.items():
        if suite in ("timestamp", "host", "wall_seconds"):
            continue
        fails = _suite_failures(result) if isinstance(result, dict) \
            else []
        failures.extend(f"{suite}: {f}" for f in fails)
        for paths, name in _KEY_METRICS.get(suite, []):
            node = result
            for k in paths:
                node = node.get(k) if isinstance(node, dict) else None
            if isinstance(node, (int, float)) and not isinstance(
                    node, bool):
                summary[f"{suite}.{name}"] = node
    return {"metric": "bench_suite",
            "timestamp": out.get("timestamp"),
            "code": _code_hash(),
            "quick": quick,
            "wall_seconds": out.get("wall_seconds"),
            "suites": sorted(k for k in out if k not in
                             ("timestamp", "host", "wall_seconds")),
            "key_metrics": summary,
            "failures": failures}


def _append_bench_log(path: str, row: dict, out: dict,
                      quick: bool) -> None:
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(row) + "\n")
    # the storm phase's per-class SLO verdict rides along as its own
    # scorecard row (availability / p99 attainment / burn per class,
    # joined to the fleet's htpu_build_info hash)
    slo = (out.get("serving_storm") or {}).get("slo") \
        if isinstance(out.get("serving_storm"), dict) else None
    if slo:
        from benchmarks.bench_trend import append_slo_scorecard
        append_slo_scorecard(path, slo, quick=quick)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="STORAGE_BENCH.json")
    ap.add_argument("--log", default="BENCH_LOG.jsonl",
                    help="bench trajectory log (one summary row per "
                         "suite run, appended)")
    ap.add_argument("--quick", action="store_true",
                    help="smaller sizes for smoke runs")
    args = ap.parse_args()

    import sys

    from benchmarks import dfsio, nn_throughput, rpc_bench, terasort_bench

    # The whole "cluster" shares one interpreter here, so a packet's hop
    # chain is a chain of GIL handoffs; the default 5 ms switch interval
    # adds up to 15 ms/packet of scheduling latency on a ~3 ms work path.
    # Real deployments run one process per daemon and never see this.
    sys.setswitchinterval(0.001)

    scale = 0.2 if args.quick else 1.0
    out = {"timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
           "host": platform.node()}
    t0 = time.perf_counter()
    out["nn_throughput_ops_per_sec"] = nn_throughput.run(
        n_ops=int(5000 * scale))
    out["rpc"] = rpc_bench.run(seconds=5.0 * scale)
    from benchmarks import mprpc_bench
    out["rpc_multiprocess"] = mprpc_bench.run(seconds=5.0 * scale,
                                              workers=4)
    from benchmarks import mini_rpc_bench
    out["rpc_connection_setup"] = mini_rpc_bench.run(
        samples=int(30 * scale) or 10)
    out["dfsio"] = dfsio.run(n_files=4, mb_per_file=int(16 * scale) or 2)
    from benchmarks import codec_bench
    out["codecs"] = codec_bench.run(mb=int(64 * scale) or 8)
    # 400 MB: big enough that scheduling/launch overhead amortizes (the
    # canonical benchmark is run at terabyte scale for the same reason)
    out["terasort"] = terasort_bench.run(records=int(4_000_000 * scale))
    # SLS: the REAL RM behind its RPC services under a 1,000-node
    # simulated fleet (ref: SLSRunner.java). (The scheduler-direct mode
    # stays available as `python -m hadoop_tpu.tools.sls` for
    # interactive what-ifs; the RM-RPC number is the recorded one.)
    from hadoop_tpu.tools import sls
    out["sls"] = sls.run_rm(num_nodes=int(1000 * scale) or 200,
                            num_apps=int(40 * scale) or 8,
                            containers_per_app=50, sweeps=20)
    # Dynamometer: >=100K-op audit replay against a real NameNode over
    # real RPC (ref: hadoop-dynamometer AuditReplayMapper).
    out["dynamometer"] = _dynamometer(int(100_000 * scale) or 20_000)
    from benchmarks import nn_bench
    out["nnbench"] = nn_bench.run(maps=4, ops_per_map=int(200 * scale)
                                  or 40)
    # Serving plane: tiny-config shared-prefix smoke (compile-once per
    # shape + hit-rate > 0 + fewer engine steps with the prefix cache)
    # so decode-path perf regressions surface in the bench trajectory.
    # A smoke failure is recorded, not raised — it must not discard the
    # benches already computed above.
    try:
        from benchmarks import serve_bench
        out["serving"] = serve_bench.run_smoke()
    except Exception as e:  # noqa: BLE001 — any serving failure (even
        # an import-time one) is a data point for the trajectory, never
        # a reason to lose the storage/compute numbers computed above
        out["serving"] = {"error": f"{type(e).__name__}: {e}"}
    # Speculative-decoding smoke: same repetitive workload with the
    # speculation lane off then on — greedy outputs must match
    # token-for-token, speculation must strictly reduce engine steps
    # with at least one accepted draft, and both step shapes compile
    # exactly once. Recorded, not raised.
    try:
        from benchmarks import serve_bench
        out["serving_speculate"] = serve_bench.run_speculate_smoke()
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["serving_speculate"] = {"error": f"{type(e).__name__}: {e}"}
    # Weight-plane smoke: the same tiny model served from f32- and
    # int8-resident weights under one fixed HBM budget — the int8 arm
    # must admit >= 2x the lanes x context (and KV blocks), the logits
    # A-B guard must accept the greedy outputs, and both step shapes
    # compile exactly once on both arms. Recorded, not raised.
    try:
        from benchmarks import serve_bench
        out["serving_quantized"] = serve_bench.run_quantized_smoke()
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["serving_quantized"] = {"error": f"{type(e).__name__}: {e}"}
    # MoE serving smoke: one int8-expert checkpoint served sparse vs
    # dense-compute — the quantized all2all payload must measure >= 2x
    # below the f32 reference on the comm ledger, the logits A-B guard
    # must accept (and its zeroed-payload falsifier reject), and both
    # step shapes compile exactly once on both arms. Recorded, not
    # raised.
    try:
        from benchmarks import serve_bench
        out["serving_moe"] = serve_bench.run_moe_smoke()
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["serving_moe"] = {"error": f"{type(e).__name__}: {e}"}
    # Replica-churn smoke: kill/restart an engine mid shared-prefix
    # workload over a miniDFS-backed KV store — fleet hit-rate must
    # recover via the DFS tier (post-restart hits > 0, strictly fewer
    # engine steps than the DFS-off arm). Recorded, not raised.
    try:
        from benchmarks import serve_bench
        out["serving_churn"] = serve_bench.run_churn_smoke()
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["serving_churn"] = {"error": f"{type(e).__name__}: {e}"}
    # Long-context smoke: a prompt 8x one chip's KV budget prefilled
    # context-parallel across an 8-dev subprocess mesh, KV streamed
    # into the host/DFS tiers, decoded through the real door with an
    # exact single-chip reference match, CP guards accepted, hit-tier
    # counters live, and every longctx shape compiled exactly once.
    # Recorded, not raised.
    try:
        from benchmarks import longctx_smoke
        out["serving_longctx"] = longctx_smoke.run(quick=args.quick)
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["serving_longctx"] = {"error": f"{type(e).__name__}: {e}"}
    # Elastic-fleet storm smoke: step-function load against a mini-fleet
    # of real `hadoop-tpu serve` subprocesses + the autoscaler — fleet
    # must grow, hold TTFT p99 within the SLO after settling, scale back
    # in via the drain protocol with zero failed requests + post-drain
    # DFS hit-rate recovery, and shed a heavy tenant (429) under
    # overload before a light tenant degrades. Recorded, not raised.
    try:
        from benchmarks import serve_bench
        out["serving_storm"] = serve_bench.run_storm_smoke()
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["serving_storm"] = {"error": f"{type(e).__name__}: {e}"}
    # Training plane: 8-virtual-device overlap smoke (A-B step counts +
    # bit-exact loss parity with the communication-overlap pass on vs
    # off, plus the async-save blocking-time split). Same recorded-not-
    # raised contract as the serving smoke.
    try:
        from benchmarks import overlap_smoke
        out["overlap"] = overlap_smoke.run()
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["overlap"] = {"error": f"{type(e).__name__}: {e}"}
    # Relaxed-parity plane: loss-curve A-B acceptance (dp2×tp2 +
    # zero1-dp8 + pp grad buckets, 50 steps) with the ≥2× quantized
    # payload-byte contract and the bitwise-tier byte-identity proof.
    # Both tiers ride every future run of this ladder. Recorded, not
    # raised.
    try:
        from benchmarks import lowp_smoke
        out["lowp"] = lowp_smoke.run()
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["lowp"] = {"error": f"{type(e).__name__}: {e}"}
    # Telemetry plane: tracing-on vs tracing-off step + DFS write/read
    # cost, with the <5% step-overhead bound recorded in the JSON
    # (exemplar bookkeeping now rides the on-arm — same bound).
    # Recorded-not-raised like the other smokes.
    try:
        from benchmarks import trace_overhead
        out["trace_overhead"] = trace_overhead.run(quick=args.quick)
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["trace_overhead"] = {"error": f"{type(e).__name__}: {e}"}
    # Fleet doctor: miniDFS + one injected-slow DN — exactly that DN
    # flagged within bounded report windows, NN placement deprioritizes
    # it, and a /prom exemplar resolves to an assembled cross-daemon
    # trace. Recorded-not-raised.
    try:
        from benchmarks import doctor_smoke
        out["doctor"] = doctor_smoke.run(quick=args.quick)
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["doctor"] = {"error": f"{type(e).__name__}: {e}"}
    # Training flight recorder: four subprocess trainer ranks, one with
    # injected per-step latency — the doctor must flag exactly that
    # rank within 3 observation windows and unflag it within the
    # hysteresis history; the slow rank's htpu_comm collective tail
    # must carry a doctor-resolvable exemplar. Recorded-not-raised.
    try:
        from benchmarks import flight_smoke
        out["flight_recorder"] = flight_smoke.run(quick=args.quick)
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["flight_recorder"] = {"error": f"{type(e).__name__}: {e}"}
    # Elastic training plane: slow→demote (protective snapshot), kill→
    # evict onto the largest healthy sub-mesh (dp 4→3, non-power-of-two)
    # with reshard-on-restore — loss-curve A-B guard vs an uninterrupted
    # twin must ACCEPT and the elastic arm must lose strictly fewer
    # steps than restart-from-checkpoint. On a no-vma jax the child
    # records skipped(env: no-vma) and stays green. Recorded-not-raised.
    try:
        from benchmarks import flight_smoke
        out["flight_elastic"] = flight_smoke.run_elastic(
            quick=args.quick)
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["flight_elastic"] = {"error": f"{type(e).__name__}: {e}"}
    # Static-analysis plane: tpulint self-run (all checkers against the
    # committed baseline) + the conf-registry drift gate, timed so a
    # creeping lint cost, a dirty tree, or a stale registry surfaces in
    # the bench trajectory. Recorded-not-raised.
    try:
        out["lint"] = _lint_selfrun()
    except Exception as e:  # noqa: BLE001 — recorded for the
        # trajectory; must not discard the benches already computed
        out["lint"] = {"error": f"{type(e).__name__}: {e}"}
    out["wall_seconds"] = round(time.perf_counter() - t0, 1)
    # One summary row per suite run into the bench trajectory log: the
    # log used to carry only hand-stamped train rows, so a regression
    # BETWEEN issues was invisible until someone re-ran a bench by
    # hand. Key metrics + failures per suite, appended, never rewritten.
    # The trend sentinel judges the new row against the history BEFORE
    # it lands — recorded, not raised: a regression between issues is a
    # data point in the trajectory, never a reason to lose the run.
    row = None
    try:
        from benchmarks import bench_trend
        row = _bench_row(out, quick=args.quick)
        out["bench_trend"] = bench_trend.check(
            bench_trend.load_rows(args.log) + [row])
    except Exception as e:  # noqa: BLE001 — the sentinel is
        # best-effort; a full bench run must never die on it
        out["bench_trend"] = {"error": f"{type(e).__name__}: {e}"}
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    try:
        if row is not None:
            _append_bench_log(args.log, row, out, quick=args.quick)
    except Exception as e:  # noqa: BLE001 — the trajectory log is
        # best-effort; a full bench run must never die on it
        print(f"BENCH_LOG append failed: {type(e).__name__}: {e}")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
