"""Offline serving benchmark: throughput + TTFT on synthetic traffic.

Drives the continuous-batching engine the way a replica would see load:
N requests submitted up front, the scheduler admitting them into the
fixed slot batch as pages free up, prefill proceeding in fixed-size
chunks fused into the decode step. Reports tokens/sec, TTFT p50/p99
(includes queue wait — the number a user feels), mean batch occupancy,
prefix-cache hit rate, and asserts the step compiled exactly once
across the whole run.

Two workload modes:

- default: mixed-length independent prompts (admission order and page
  pressure vary per request).
- ``--shared-prefix``: grouped prompts sharing a long common head (the
  production shape: shared system prompts, few-shot preambles, retry
  storms). Runs the SAME workload twice — prefix cache disabled, then
  enabled — and reports the TTFT delta the cache buys plus the hit
  rate; exits nonzero unless the deterministic contract holds (hit
  rate positive, strictly fewer engine steps with the cache, both
  shapes compiled exactly once). A wall-clock TTFT inversion is
  reported as a warning, not a failure (host-load noise).
- ``--quantized``: the weight-plane A-B (serving/weightplane.py) — the
  same model served from f32- and int8-resident weights under ONE
  fixed HBM budget; fails unless the int8 arm admits >= 2x the
  lanes x context (and KV blocks), the logits A-B guard accepts the
  greedy outputs, and both shapes compile exactly once on both arms.
- ``--moe``: the MoE serving A-B — one int8-expert checkpoint served
  sparse (config top_k) vs dense-compute (top_k = n_experts) at the
  same parameters; fails unless the relaxed-tier quantized all2all
  payload measures >= 2x below the f32 reference on the comm ledger
  (``moe.dispatch``/``moe.combine`` sites), the logits A-B guard
  accepts and its zeroed-expert-payload falsifier rejects, and both
  step shapes compile exactly once on both arms.
- ``--longctx``: the long-context arm (``benchmarks/longctx_smoke``,
  8-virtual-device subprocess): a prompt 8x one chip's KV budget
  prefilled context-parallel across the mesh, KV streamed into the
  host/DFS tiers, decoded through the real door with an exact
  single-chip reference match; CP guards + compile-once + hit-tier
  counters asserted, TTFT-by-chips recorded.

Runs under JAX_PLATFORMS=cpu (tiny preset) or on real hardware with a
bigger preset. JSON output matches the BENCH_*.json shape::

    JAX_PLATFORMS=cpu python benchmarks/serve_bench.py
    JAX_PLATFORMS=cpu python benchmarks/serve_bench.py --shared-prefix
    python benchmarks/serve_bench.py --preset flagship-420m --requests 64
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python benchmarks/serve_bench.py` from the repo root too
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _percentile(sorted_vals, p):
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(p * len(sorted_vals)))]


def _make_prompts(rng, cfg, s_max, requests, max_new, shared_prefix,
                  prefix_groups, shared_len, repetitive=False,
                  motif_len=4, prompt_len=24):
    """Mixed-length independent prompts, grouped prompts sharing a
    long head, or repetitive motif prompts (``repetitive``: each
    prompt tiles a random ``motif_len``-token motif — the
    acceptance-friendly shape for prompt-lookup speculation: templated
    traffic and the short cycles greedy decode settles into). Group
    order is interleaved (g0 r0, g1 r0, ..., g0 r1, ...) so every
    group's first request prefills cold before its siblings arrive —
    the cache is earning hits, not being handed them."""
    import numpy as np
    if repetitive:
        plen = max(motif_len, min(prompt_len, s_max - max_new - 1))
        out = []
        for _ in range(requests):
            m = rng.integers(0, cfg.vocab_size, size=motif_len).tolist()
            out.append((m * (-(-plen // motif_len)))[:plen])
        return out
    if not shared_prefix:
        max_prompt = max(2, s_max - max_new - 1)
        return [rng.integers(0, cfg.vocab_size,
                             size=int(rng.integers(2, max_prompt + 1))
                             ).tolist()
                for _ in range(requests)]
    tail_max = max(2, min(12, s_max - max_new - shared_len - 1))
    heads = [rng.integers(0, cfg.vocab_size, size=shared_len).tolist()
             for _ in range(prefix_groups)]
    prompts = []
    for i in range(requests):
        head = heads[i % prefix_groups]
        tail = rng.integers(0, cfg.vocab_size,
                            size=int(rng.integers(2, tail_max + 1))
                            ).tolist()
        prompts.append(head + tail)
    return prompts


def run(preset="tiny", requests=24, max_new=32, max_batch=8,
        block_size=16, max_context=128, chunk=16, seed=0,
        shared_prefix=False, prefix_groups=4, shared_len=48,
        prefix_cache=True, speculate_k=0, speculate_ngram=3,
        repetitive=False, motif_len=4, prompt_len=24,
        collect_outputs=False) -> dict:
    """One engine, one workload; returns the result dict."""
    import jax
    import numpy as np

    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.models.decoder import count_params, init_params
    from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams
    from hadoop_tpu.serving.metrics import ServingMetrics

    cfg = get_config(preset)
    rng = np.random.default_rng(seed)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    engine = DecodeEngine(params, cfg, max_batch=max_batch,
                          block_size=block_size,
                          max_context=min(max_context, cfg.max_seq),
                          prefill_chunk=chunk,
                          prefix_cache=prefix_cache,
                          speculate_k=speculate_k,
                          speculate_ngram=speculate_ngram,
                          metrics=ServingMetrics())
    sampling = SamplingParams(max_new_tokens=max_new)
    prompts = _make_prompts(rng, cfg, engine.s_max, requests, max_new,
                            shared_prefix, prefix_groups, shared_len,
                            repetitive, motif_len, prompt_len)

    # warmup: trigger the step compile outside the timed window (too
    # short to seed the prefix cache: 2 tokens never fill a block)
    engine.generate([prompts[0][:2]], SamplingParams(max_new_tokens=2))

    t0 = time.monotonic()
    reqs = [engine.submit(p, sampling) for p in prompts]
    steps0 = engine.steps
    while not all(r.done.is_set() for r in reqs):
        engine.step()
    elapsed = time.monotonic() - t0

    tokens = sum(len(r.out_tokens) for r in reqs)
    ttfts_ms = sorted((r.first_token_at - r.submitted_at) * 1e3
                      for r in reqs)
    occ = engine.occupancy_log
    cache = engine.cache_stats()
    dev = jax.devices()[0]
    return {
        "metric": "serve_tokens_per_sec",
        "value": round(tokens / elapsed, 1),
        "unit": "tokens/s",
        "preset": preset,
        "n_params": count_params(params),
        "requests": requests,
        "max_new": max_new,
        "batch_slots": max_batch,
        "kv_block_size": block_size,
        "prefill_chunk": chunk,
        "prefix_cache_enabled": prefix_cache,
        "shared_prefix": shared_prefix,
        "prompt_tokens": sum(len(p) for p in prompts),
        "generated_tokens": tokens,
        "elapsed_s": round(elapsed, 3),
        "decode_steps": engine.steps - steps0,
        "ttft_p50_ms": round(_percentile(ttfts_ms, 0.50), 2),
        "ttft_p99_ms": round(_percentile(ttfts_ms, 0.99), 2),
        "occupancy_mean": round(float(np.mean(occ)), 2) if occ else 0.0,
        # engine-local, not the process-global metrics counter: two
        # runs in one process (the cache-on/off comparison) must not
        # bleed counts into each other
        "preemptions": sum(r.preemptions for r in reqs),
        "prefix_cache_hit_rate": round(cache["hit_rate"], 4),
        "prefix_tokens_matched": cache["tokens_matched"],
        "prefix_cache_evictions": cache["evictions"],
        "decode_compiles": engine.decode_compiles,
        "prefill_compiles": engine.prefill_compiles,
        "speculate_k": speculate_k,
        "spec_proposed": engine.spec_proposed,
        "spec_accepted": engine.spec_accepted,
        "spec_accept_rate": round(
            engine.spec_accepted / engine.spec_proposed, 4)
            if engine.spec_proposed else 0.0,
        "device": getattr(dev, "device_kind", str(dev)),
        # per-request token streams when the caller A-Bs two arms for
        # token-for-token equality (omitted otherwise: the default JSON
        # should not carry thousands of tokens)
        **({"outputs": [r.wait(0) for r in reqs]}
           if collect_outputs else {}),
    }


def run_shared_prefix(**kw) -> dict:
    """The cache-value measurement: same seed/config/workload twice —
    prefix cache off, then on. ``failed`` (the CI/exit-code contract)
    carries only DETERMINISTIC checks: compile-once per shape, positive
    hit rate, and a strictly lower engine step count with the cache
    (skipped prefill chunks always mean fewer steps — the
    noise-immune form of the TTFT win). The wall-clock TTFT p50
    comparison is reported, and an inversion lands in ``warnings``
    (advisory: a loaded host can blur millisecond timings even while
    the cache is demonstrably working)."""
    kw["shared_prefix"] = True
    no_cache = run(prefix_cache=False, **kw)
    cache = run(prefix_cache=True, **kw)
    warnings = []
    if cache["requests"] <= cache["batch_slots"]:
        # every request admits into a free slot before any sibling's
        # prefill publishes its blocks — the whole wave runs cold and
        # the hit-rate/steps contract below cannot hold
        warnings.append(
            f"requests ({cache['requests']}) <= batch slots "
            f"({cache['batch_slots']}): the entire workload admits "
            f"cold; use more requests than slots to measure reuse")
    result = {
        "metric": "serve_shared_prefix_ttft_p50_ms",
        "value": cache["ttft_p50_ms"],
        "unit": "ms",
        "no_cache": no_cache,
        "cache": cache,
        "ttft_p50_delta_ms": round(
            no_cache["ttft_p50_ms"] - cache["ttft_p50_ms"], 2),
        "steps_delta": no_cache["decode_steps"] - cache["decode_steps"],
        "prefix_cache_hit_rate": cache["prefix_cache_hit_rate"],
        "failed": [],
        "warnings": warnings,
    }
    for name, r in (("no_cache", no_cache), ("cache", cache)):
        for counter in ("decode_compiles", "prefill_compiles"):
            if r[counter] != 1:
                result["failed"].append(
                    f"{name}: {counter} == {r[counter]} (expected "
                    f"exactly 1 — shape retracing crept in)")
    if cache["prefix_cache_hit_rate"] <= 0:
        result["failed"].append("prefix cache never hit on a "
                                "shared-prefix workload")
    if cache["decode_steps"] >= no_cache["decode_steps"]:
        result["failed"].append(
            f"prefix cache did not reduce engine steps: "
            f"{cache['decode_steps']} vs {no_cache['decode_steps']} "
            f"without it")
    if cache["ttft_p50_ms"] >= no_cache["ttft_p50_ms"]:
        result["warnings"].append(
            f"TTFT p50 wall-clock did not improve this run: cache "
            f"{cache['ttft_p50_ms']}ms vs no-cache "
            f"{no_cache['ttft_p50_ms']}ms (host load noise; the step "
            f"count fell {no_cache['decode_steps']} -> "
            f"{cache['decode_steps']})")
    return result


def run_speculate(preset="tiny", requests=8, max_new=96, max_batch=2,
                  block_size=8, max_context=128, chunk=16, seed=0,
                  spec_k=4, motif_len=2, prompt_len=24,
                  reps=3) -> dict:
    """The speculation-value measurement: the SAME repetitive workload
    (tiled random motifs — the acceptance-friendly shape: templated
    traffic, retrieval echoes, the cycles greedy decode settles into)
    twice at low occupancy — speculation off, then on. ``failed`` (the
    CI/exit-code contract) carries only DETERMINISTIC checks: greedy
    outputs token-for-token identical (the exactness pin — speculation
    may only move WORK, never tokens), STRICTLY fewer engine steps with
    speculation (each accepted draft skips a whole step — the
    noise-immune form of the tokens/s win), at least one accepted
    draft, and compile-once per shape on both arms. The wall-clock
    tokens/s ratio is reported against the >1.5x target; a shortfall
    lands in ``warnings`` (advisory: a loaded host can blur the timing
    even while the step count proves the win)."""
    import statistics
    kw = dict(preset=preset, requests=requests, max_new=max_new,
              max_batch=max_batch, block_size=block_size,
              max_context=max_context, chunk=chunk, seed=seed,
              repetitive=True, motif_len=motif_len,
              prompt_len=prompt_len, collect_outputs=True)
    # interleave the arms, median the wall-clock (dfsio precedent: a
    # contended box drifts minute to minute, and drift must not read
    # as a speculation win or loss); tokens/steps are deterministic,
    # so every rep's outputs must agree anyway
    offs, ons = [], []
    for _ in range(max(1, reps)):
        offs.append(run(speculate_k=0, **kw))
        ons.append(run(speculate_k=spec_k, **kw))
    off = dict(offs[0], value=round(statistics.median(
        r["value"] for r in offs), 1))
    on = dict(ons[0], value=round(statistics.median(
        r["value"] for r in ons), 1))
    ratio = round(on["value"] / off["value"], 3) if off["value"] else 0.0
    result = {
        "metric": "serve_speculate_tokens_per_sec",
        "value": on["value"],
        "unit": "tokens/s",
        "preset": preset,
        "spec_k": spec_k,
        "tokens_per_sec_off": off["value"],
        "tokens_per_sec_ratio": ratio,
        "steps_off": off["decode_steps"],
        "steps_on": on["decode_steps"],
        "steps_ratio": round(off["decode_steps"] /
                             max(1, on["decode_steps"]), 3),
        "spec_proposed": on["spec_proposed"],
        "spec_accepted": on["spec_accepted"],
        "spec_accept_rate": on["spec_accept_rate"],
        "failed": [],
        "warnings": [],
    }
    if on["outputs"] != off["outputs"]:
        result["failed"].append(
            "speculation changed greedy output tokens — the verifier "
            "is accepting drafts the model would not have emitted")
    if any(r["outputs"] != off["outputs"] for r in offs[1:]) or \
            any(r["outputs"] != on["outputs"] for r in ons[1:]):
        result["failed"].append(
            "outputs drifted across reps of the same arm — greedy "
            "decode went nondeterministic")
    if on["decode_steps"] >= off["decode_steps"]:
        result["failed"].append(
            f"speculation did not reduce engine steps: "
            f"{on['decode_steps']} vs {off['decode_steps']} without it")
    if on["spec_accepted"] <= 0:
        result["failed"].append(
            "no draft token was ever accepted on a repetitive workload")
    for name, r in (("off", off), ("on", on)):
        for counter in ("decode_compiles", "prefill_compiles"):
            if r[counter] != 1:
                result["failed"].append(
                    f"{name}: {counter} == {r[counter]} (expected "
                    f"exactly 1 — shape retracing crept in)")
    if ratio < 1.5:
        result["warnings"].append(
            f"tokens/s ratio {ratio} below the 1.5x target this run "
            f"(host load noise; the step count fell "
            f"{off['decode_steps']} -> {on['decode_steps']})")
    for r in (off, on):
        del r["outputs"]
    result["off"], result["on"] = off, on
    return result


def run_speculate_smoke() -> dict:
    """Tiny-config speculation smoke for benchmarks.run_all: raises
    unless the deterministic contract holds (token-identical greedy
    output, strictly fewer engine steps, accepted drafts > 0,
    compile-once per shape). One rep at half the decode depth — the
    contract is deterministic, so the CLI's median-of-3 timing shape
    buys nothing here (run_smoke precedent); the tokens/s ratio rides
    along for the trajectory."""
    result = run_speculate(preset="tiny", max_new=48, reps=1)
    if result["failed"]:
        raise AssertionError("; ".join(result["failed"]))
    return result


def run_quantized(preset="tiny", requests=24, max_new=12, block_size=4,
                  max_context=64, chunk=8, seed=0, group=16,
                  f32_lanes=2, max_lanes=16) -> dict:
    """The weight-plane capacity measurement: the SAME model and
    workload served from f32-resident weights and from int8-resident
    weights (serving/weightplane.py, full policy: layer matmuls +
    embedding + head) under ONE fixed HBM budget — f32 weights plus
    ``f32_lanes`` full-context lanes of KV. The engine sizes its KV
    pool and decode lanes against the MEASURED resident-weight bytes,
    so the int8 arm's freed HBM shows up directly as lanes x context.

    The hard capacity contract (``failed``, all deterministic):

    - the int8 arm admits >= 2x the lanes x context of the f32 arm at
      the same ``serving.kv.hbm.bytes``-equivalent budget (and >= 2x
      the usable KV blocks);
    - greedy-output acceptance via the logits A-B guard
      (``run_weight_ab``: teacher-forced argmax agreement + bounded
      logit divergence over identical inputs);
    - both step shapes compile exactly once on both arms.

    tokens/s is reported for both arms (wall-clock — advisory on a
    contended CPU box; the capacity numbers are the stable signal)."""
    import jax
    import numpy as np

    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.models.decoder import count_params, init_params
    from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams
    from hadoop_tpu.serving.weightplane import (WeightPlaneConfig,
                                                quantize_params,
                                                resident_weight_bytes,
                                                run_weight_ab)

    cfg = get_config(preset)
    rng = np.random.default_rng(seed)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    wp = WeightPlaneConfig(tier="relaxed", group=group,
                           quant_embed=True, quant_head=True)
    qparams, qreport = quantize_params(params, cfg, wp)
    wb_f32 = resident_weight_bytes(params)
    wb_int8 = resident_weight_bytes(qparams)
    # one budget for both arms: f32 weights + f32_lanes full-context
    # lanes of KV (+ scratch/slack) — what a chip sized for the f32
    # model actually has
    bps = -(-min(max_context, cfg.max_seq) // block_size)
    block_nbytes = (2 * cfg.n_layers * block_size * cfg.n_kv_heads *
                    cfg.head_dim * jax.numpy.dtype(cfg.jax_dtype).itemsize)
    budget = wb_f32 + (f32_lanes * bps + 2) * block_nbytes

    sampling = SamplingParams(max_new_tokens=max_new)
    s_max = bps * block_size
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=int(rng.integers(4, max(5, s_max
                                                         - max_new - 1)))
                            ).tolist()
               for _ in range(requests)]

    def arm(p, quantize_seconds=0.0):
        eng = DecodeEngine(p, cfg, max_batch=None, block_size=block_size,
                           max_context=max_context, prefill_chunk=chunk,
                           hbm_bytes=budget, max_lanes=max_lanes,
                           quantize_seconds=quantize_seconds)
        eng.generate([prompts[0][:2]], SamplingParams(max_new_tokens=2))
        t0 = time.monotonic()
        reqs = [eng.submit(pr, sampling) for pr in prompts]
        steps0 = eng.steps
        while not all(r.done.is_set() for r in reqs):
            eng.step()
        elapsed = time.monotonic() - t0
        tokens = sum(len(r.out_tokens) for r in reqs)
        plane = eng.weight_plane()
        return {
            "tokens_per_sec": round(tokens / elapsed, 1),
            "elapsed_s": round(elapsed, 3),
            "decode_steps": eng.steps - steps0,
            "lanes": eng.max_batch,
            "max_context": eng.s_max,
            "lanes_x_context": plane["lanes_x_context"],
            "kv_blocks": eng.pool.num_usable,
            "kv_capacity_tokens": plane["kv_capacity_tokens"],
            "weight_bytes": plane["weight_bytes"],
            "weight_dtype": plane["dtype"],
            "decode_compiles": eng.decode_compiles,
            "prefill_compiles": eng.prefill_compiles,
        }

    f32 = arm(params)
    int8 = arm(qparams, qreport["quantize_seconds"])
    guard = run_weight_ab(cfg, params, qparams, seed=seed, wp=wp)
    cap_ratio = int8["lanes_x_context"] / max(1, f32["lanes_x_context"])
    blocks_ratio = int8["kv_blocks"] / max(1, f32["kv_blocks"])
    failed = []
    if cap_ratio < 2.0:
        failed.append(
            f"int8 arm admits only {cap_ratio:.2f}x the lanes x context "
            f"of the f32 arm at the same HBM budget (contract: >= 2x)")
    if blocks_ratio < 2.0:
        failed.append(
            f"int8 arm holds only {blocks_ratio:.2f}x the KV blocks of "
            f"the f32 arm at the same HBM budget (contract: >= 2x)")
    if not guard.get("accepted"):
        failed.append(f"logits/output A-B guard rejected the int8 "
                      f"weight plane: {guard.get('reason')}")
    for name, r in (("f32", f32), ("int8", int8)):
        for counter in ("decode_compiles", "prefill_compiles"):
            if r[counter] != 1:
                failed.append(
                    f"{name}: {counter} == {r[counter]} (expected "
                    f"exactly 1 — shape retracing crept in)")
    return {
        "metric": "serve_quantized_capacity_ratio",
        "value": round(cap_ratio, 3),
        "unit": "x lanes*context at fixed HBM",
        "preset": preset,
        "n_params": count_params(params),
        "hbm_budget_bytes": int(budget),
        "weight_bytes_f32": wb_f32,
        "weight_bytes_int8": wb_int8,
        "weight_bytes_ratio": round(wb_f32 / wb_int8, 3),
        "quantize_seconds": qreport["quantize_seconds"],
        "kv_blocks_ratio": round(blocks_ratio, 3),
        "tokens_per_sec_f32": f32["tokens_per_sec"],
        "tokens_per_sec_int8": int8["tokens_per_sec"],
        "weight_plane": {k: v for k, v in qreport.items()
                         if not k.startswith("_")},
        "guard": guard,
        "f32": f32,
        "int8": int8,
        "failed": failed,
    }


def run_quantized_smoke() -> dict:
    """Tiny-config weight-plane smoke for benchmarks.run_all: raises
    unless the capacity contract holds (>= 2x lanes x context and KV
    blocks at fixed HBM, logits A-B guard accepted, compile-once per
    shape on both arms)."""
    result = run_quantized(preset="tiny")
    if result["failed"]:
        raise AssertionError("; ".join(result["failed"]))
    return result


def run_moe(preset="tiny-moe", requests=16, max_new=12, block_size=4,
            chunk=8, max_context=64, max_batch=2, group=16,
            seed=0) -> dict:
    """MoE serving A-B: dense-compute vs sparse dispatch at equal
    quality, plus the relaxed-tier all2all byte contract.

    One MoE checkpoint, int8-quantized expert stacks
    (serving/weightplane.py — the expert dims quantize through the same
    policy table as dense), served twice with identical weights:

    - ``sparse``: the config's top_k (the production shape — each token
      computes only its routed experts' FLOPs);
    - ``dense``:  top_k = n_experts (every expert active for every
      token — the dense-equivalent compute at the same parameters, the
      cost baseline sparse routing is supposed to beat).

    The hard contract (``failed``, all deterministic):

    - the quantized all2all dispatch/combine payloads measure >= 2x
      below the f32 reference bytes ON THE COMM LEDGER
      (``moe.dispatch``/``moe.combine`` sites, payload/reference/
      executions dimensions — int8 payload + one f32 scale per
      (expert, slot) row vs the f32 exchange);
    - greedy-output acceptance via the logits A-B guard
      (``run_weight_ab``; MoE thresholds — routing flips at near-tie
      tokens cause localized logit spikes, so the rel-err bound is
      wide and the argmax-agreement dimension carries the systematic-
      damage check);
    - falsifiability: the same guard REJECTS a zeroed expert payload
      (w_down int8 bytes zeroed, scales kept) — proof the acceptance
      above is a real measurement, not a rubber stamp;
    - both step shapes compile exactly once on both arms (capacity
      padding keeps the routed step's shapes static).

    tokens/s for both arms is wall-clock — advisory on a contended CPU
    box; the ledger byte ratio and the guard verdicts are the stable
    signal (sparse-slower-than-dense at toy scale is a warning, not a
    failure: with 4 tiny experts the routing einsums dominate)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.models.decoder import count_params, init_params
    from hadoop_tpu.parallel.lowp.quant import capture_comm
    from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams
    from hadoop_tpu.serving.weightplane import (EXPERT_STACKS,
                                                WeightPlaneConfig,
                                                expert_weight_bytes,
                                                quantize_params,
                                                run_weight_ab)

    cfg = get_config(preset)
    if not cfg.is_moe:
        raise ValueError(f"--moe needs an MoE preset, got {preset!r}")
    rng = np.random.default_rng(seed)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    wp = WeightPlaneConfig(tier="relaxed", group=group)
    qparams, qreport = quantize_params(params, cfg, wp)

    sampling = SamplingParams(max_new_tokens=max_new)
    s_max = -(-min(max_context, cfg.max_seq) // block_size) * block_size
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=int(rng.integers(4, max(5, s_max
                                                         - max_new - 1)))
                            ).tolist()
               for _ in range(requests)]

    def arm(arm_cfg):
        eng = DecodeEngine(qparams, arm_cfg, max_batch=max_batch,
                           block_size=block_size,
                           max_context=max_context, prefill_chunk=chunk,
                           quantize_seconds=qreport["quantize_seconds"])
        eng.generate([prompts[0][:2]], SamplingParams(max_new_tokens=2))
        t0 = time.monotonic()
        reqs = [eng.submit(pr, sampling) for pr in prompts]
        while not all(r.done.is_set() for r in reqs):
            eng.step()
        elapsed = time.monotonic() - t0
        tokens = sum(len(r.out_tokens) for r in reqs)
        plane = eng.weight_plane()
        return {
            "tokens_per_sec": round(tokens / elapsed, 1),
            "elapsed_s": round(elapsed, 3),
            "top_k": arm_cfg.top_k,
            "expert_capacity": plane["expert_capacity"],
            "decode_compiles": eng.decode_compiles,
            "prefill_compiles": eng.prefill_compiles,
        }

    sparse = arm(cfg)
    dense = arm(dataclasses.replace(cfg, top_k=cfg.n_experts))

    # ---- the all2all byte contract, measured on the comm ledger: one
    # fresh engine traced (both shapes) inside the capture window — the
    # ledger's executions dimension counts what the hardware runs per
    # step (n_layers legs via comm_scale), the ratio is reference/payload
    with capture_comm() as led:
        eng = DecodeEngine(qparams, cfg, max_batch=max_batch,
                           block_size=block_size,
                           max_context=max_context, prefill_chunk=chunk)
        eng.generate([prompts[0][:6]], SamplingParams(max_new_tokens=4))
    a2a_sites = {s: v for s, v in led.per_site.items()
                 if s.startswith("moe.")}
    a2a_ratio = led.ratio

    # ---- acceptance + falsifiability: MoE guard thresholds are wider
    # on rel-err (near-tie routing flips spike single positions) and
    # lean on greedy agreement; the zeroed-payload arm proves the guard
    # still discriminates at these thresholds
    moe_agree, moe_rel = 0.9, 3.0
    guard = run_weight_ab(cfg, params, qparams, seed=seed, wp=wp,
                          min_agree=moe_agree, rel_tol=moe_rel)
    broken = dict(qparams)
    broken["layers"] = dict(qparams["layers"])
    wd = qparams["layers"]["w_down"]
    broken["layers"]["w_down"] = {"q": jnp.zeros_like(wd["q"]),
                                  "s": wd["s"]}
    falsifier = run_weight_ab(cfg, params, broken, seed=seed, wp=wp,
                              min_agree=moe_agree, rel_tol=moe_rel)

    failed = []
    warnings = []
    if not a2a_sites or {"moe.dispatch", "moe.combine"} - set(a2a_sites):
        failed.append(f"comm ledger missing MoE a2a sites: recorded "
                      f"{sorted(led.per_site)}")
    if a2a_ratio < 2.0:
        failed.append(
            f"quantized a2a payload is only {a2a_ratio:.2f}x below the "
            f"f32 reference on the comm ledger (contract: >= 2x)")
    if not guard.get("accepted"):
        failed.append(f"logits/output A-B guard rejected the int8 MoE "
                      f"weight plane: {guard.get('reason')}")
    if falsifier.get("accepted"):
        failed.append("falsifiability arm FAILED: the guard accepted a "
                      "zeroed expert payload — the acceptance above "
                      "proves nothing")
    for name, r in (("sparse", sparse), ("dense", dense)):
        for counter in ("decode_compiles", "prefill_compiles"):
            if r[counter] != 1:
                failed.append(
                    f"{name}: {counter} == {r[counter]} (expected "
                    f"exactly 1 — shape retracing crept in)")
    if sparse["tokens_per_sec"] < dense["tokens_per_sec"]:
        warnings.append(
            f"sparse arm ({sparse['tokens_per_sec']} tok/s) slower than "
            f"dense-compute arm ({dense['tokens_per_sec']} tok/s) — "
            f"expected at toy scale, routing overhead dominates "
            f"{cfg.n_experts} tiny experts")
    return {
        "metric": "serve_moe_a2a_payload_ratio",
        "value": round(a2a_ratio, 3),
        "unit": "x f32 reference bytes on the comm ledger",
        "preset": preset,
        "n_params": count_params(params),
        "n_experts": cfg.n_experts,
        "top_k": cfg.top_k,
        "capacity_factor": cfg.capacity_factor,
        "moe_tokens_per_sec": sparse["tokens_per_sec"],
        "dense_tokens_per_sec": dense["tokens_per_sec"],
        "moe_a2a_payload_ratio": round(a2a_ratio, 3),
        "guard_accepted": int(bool(guard.get("accepted"))),
        "falsifier_rejected": int(not falsifier.get("accepted")),
        "expert_bytes_f32": expert_weight_bytes(params, cfg),
        "expert_bytes_int8": expert_weight_bytes(qparams, cfg),
        "expert_stacks": sorted(EXPERT_STACKS),
        "a2a_sites": a2a_sites,
        "weight_plane": {k: v for k, v in qreport.items()
                         if not k.startswith("_")},
        "guard": guard,
        "falsifier": falsifier,
        "sparse": sparse,
        "dense": dense,
        "failed": failed,
        "warnings": warnings,
    }


def run_moe_smoke() -> dict:
    """Tiny MoE A-B smoke for benchmarks.run_all: raises unless the
    expert-serving contract holds (a2a payload >= 2x below reference on
    the comm ledger, guard accepted, zeroed-payload falsifier rejected,
    compile-once per shape on both arms)."""
    result = run_moe()
    if result["failed"]:
        raise AssertionError("; ".join(result["failed"]))
    return result


def run_churn(preset="tiny", prefix_groups=2, shared_len=24,
              block_size=4, chunk=4, max_new=4, max_batch=4,
              max_context=None, seed=0) -> dict:
    """Replica-churn measurement: does fleet hit-rate survive a replica
    restart? A replica dies with its HBM radix and host ring; only the
    DFS prefix store outlives it. Two arms, same seed and workload:

    - ``dfs``:  engine 1 serves wave 1 of a shared-prefix workload with
      the DFS tier on (hot heads persist through the miniDFS write
      pipeline), then is killed mid-workload. A fresh engine — cold
      HBM, pointed at the same DFS — serves wave 2 and recovers the
      shared heads with hedged reads instead of re-prefilling.
    - ``cold``: identical, DFS tier off — the restart torches
      everything and wave 2 prefills from scratch.

    The deterministic contract (``failed``): the restarted DFS-arm
    engine has post-restart hit-rate > 0 with every hit from the DFS
    tier, and spends STRICTLY fewer engine steps on wave 2 than the
    cold arm (skipped prefill chunks always mean fewer steps —
    wall-clock-noise-immune), with both step shapes compiling exactly
    once per engine."""
    import tempfile

    import jax
    import numpy as np

    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.models.decoder import init_params
    from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    cfg = get_config(preset)
    if max_context is None:
        # room for the shared head, the per-request tail, and max_new
        max_context = min(cfg.max_seq, shared_len + 16 + max_new)
    rng = np.random.default_rng(seed)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    heads = [rng.integers(0, cfg.vocab_size, size=shared_len).tolist()
             for _ in range(prefix_groups)]

    def tail():
        return rng.integers(0, cfg.vocab_size,
                            size=int(rng.integers(2, 7))).tolist()

    # wave 1 runs in two sequential half-waves: the second half's
    # requests re-match the heads the first half inserted — only that
    # CROSS-REQUEST match makes a head hot (crosses min-refs) and
    # persists it; submitting both at once would admit every request
    # cold before any sibling's prefill published its blocks
    wave1a = [h + tail() for h in heads]
    wave1b = [h + tail() for h in heads]
    wave2 = [h + tail() for h in heads for _ in range(2)]
    sampling = SamplingParams(max_new_tokens=max_new)

    def mk(fs, kvdir):
        return DecodeEngine(params, cfg, max_batch=max_batch,
                            block_size=block_size,
                            max_context=max_context, prefill_chunk=chunk,
                            kv_store_fs=fs, kv_store_dir=kvdir,
                            kv_dfs_min_refs=1)

    def wave(eng, prompts):
        s0 = eng.steps
        reqs = [eng.submit(p, sampling) for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            eng.step()
        return eng.steps - s0, [r.wait(0) for r in reqs]

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    result = {}
    with tempfile.TemporaryDirectory() as tmp, \
            MiniDFSCluster(num_datanodes=1, conf=conf,
                           base_dir=tmp) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        for arm, arm_fs in (("dfs", fs), ("cold", None)):
            e1 = mk(arm_fs, f"/kvcache-{arm}")
            w1_steps, w1_out = wave(e1, wave1a)
            w1b_steps, _ = wave(e1, wave1b)
            w1_steps += w1b_steps
            if arm_fs is not None:
                e1.kvstore.flush(60.0)
            persisted = e1.kvstore.stats()["dfs_persists"]
            e1.stop()                       # the churn: replica killed —
            del e1                          # HBM radix + host ring gone
            e2 = mk(arm_fs, f"/kvcache-{arm}")
            w2_steps, w2_out = wave(e2, wave2)
            st = e2.kvstore.stats()
            result[arm] = {
                "wave1_steps": w1_steps, "wave2_steps": w2_steps,
                "persisted_blocks": persisted,
                "post_restart_hits_dfs": st["hits_dfs"],
                "post_restart_hit_rate": round(
                    e2.prefix_tokens_matched /
                    max(1, e2.prefix_tokens_seen), 4),
                "decode_compiles": e2.decode_compiles,
                "prefill_compiles": e2.prefill_compiles,
                "outputs": w2_out,
            }
            e2.stop()
    failed = []
    d, c = result["dfs"], result["cold"]
    if d["outputs"] != c["outputs"]:
        failed.append("DFS-recovered decode diverged from the cold "
                      "decode — the tiers are corrupting KV")
    if d["post_restart_hits_dfs"] <= 0 or \
            d["post_restart_hit_rate"] <= 0:
        failed.append(
            f"hit-rate did not survive the restart: dfs hits "
            f"{d['post_restart_hits_dfs']}, rate "
            f"{d['post_restart_hit_rate']}")
    if d["wave2_steps"] >= c["wave2_steps"]:
        failed.append(
            f"post-restart steps not reduced: {d['wave2_steps']} with "
            f"the DFS tier vs {c['wave2_steps']} cold")
    for arm in ("dfs", "cold"):
        for counter in ("decode_compiles", "prefill_compiles"):
            if result[arm][counter] > 1:
                failed.append(f"{arm}: {counter} == "
                              f"{result[arm][counter]} (retracing)")
        del result[arm]["outputs"]
    return {
        "metric": "serve_churn_post_restart_steps",
        "value": d["wave2_steps"],
        "unit": "engine steps",
        "preset": preset,
        "prefix_groups": prefix_groups,
        "shared_len": shared_len,
        "steps_saved_vs_cold": c["wave2_steps"] - d["wave2_steps"],
        "dfs": d,
        "cold": c,
        "failed": failed,
    }


def run_storm(preset="tiny", slo_ttft_s=15.0, qos_slo_s=10.0,
              max_batch=4, block_size=4, chunk=8, max_context=64,
              max_new=6, storm_workers=8, markers=10, seed=0,
              spawn_timeout_s=120.0) -> dict:
    """The elastic-fleet acceptance storm, end-to-end over the REAL CLI
    path: a miniDFS (checkpoint + DFS KV tier), an in-process registry,
    replicas as ``hadoop-tpu serve`` subprocesses, and the autoscaler
    control loop driving them.

    Step-function load: a light baseline, then ``storm_workers``
    closed-loop clients slam the single replica. The hard contract:

    - the fleet GROWS (1 → 2 replicas) under the storm;
    - after the scale-out settles, fleet TTFT p99 (the autoscaler's own
      windowed signal) is within the conf'd SLO;
    - when the load drops the fleet scales back to baseline via the
      drain protocol — ZERO failed requests across the whole run;
    - post-drain the survivor recovers the drained replica's prefixes
      from the DFS tier (``hits_dfs`` delta > 0 on marker prompts whose
      rendezvous owner was the drained replica);
    - under synthetic overload, a heavy tenant is shed (429 +
      Retry-After) while a light tenant's requests all succeed with
      p99 within the QoS SLO, and the shed counter shows on ``/prom``;
    - the fleet doctor's SLO scoreboard, pumped over the same overload
      (deterministic ``poll_once`` windows — injected counters, no
      wall-clock asserts), flags the heavy class (p3) as burning its
      error budget at ``/ws/v1/fleet/slo`` while the light class (p0)
      stays green; the per-class scorecard rides the result (and lands
      in BENCH_LOG.jsonl as an ``slo_scorecard`` row).
    """
    import http.client as _http
    import statistics
    import subprocess
    import tempfile
    import threading

    import jax
    import numpy as np

    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.models.decoder import init_params
    from hadoop_tpu.parallel.checkpoint import save_checkpoint
    from hadoop_tpu.registry import RegistryServer
    from hadoop_tpu.serving.autoscale import Autoscaler, FleetActuator
    from hadoop_tpu.serving.autoscale.signals import http_get
    from hadoop_tpu.serving.router import (REGISTRY_PREFIX,
                                           ServingRouter, affinity_key,
                                           rendezvous_owner)
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    cfg = get_config(preset)
    rng = np.random.default_rng(seed)
    service = "storm"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def post_json(port, path, payload, timeout=60.0):
        conn = _http.HTTPConnection("127.0.0.1", port, timeout=timeout)
        try:
            conn.request("POST", path,
                         body=json.dumps(payload).encode())
            resp = conn.getresponse()
            body = resp.read()
            return resp.status, (json.loads(body) if body else {}), \
                resp.getheader("Retry-After")
        finally:
            conn.close()

    class ProcFleet(FleetActuator):
        """Spawn `hadoop-tpu serve` subprocesses; a drained replica
        exits itself, retire() just reaps it."""

        def __init__(self, ckpt_uri, reg_port, logdir):
            self.ckpt_uri = ckpt_uri
            self.reg_port = reg_port
            self.logdir = logdir
            self.procs = []
            self.spawned = 0

        def spawn(self, n=1):
            for _ in range(n):
                i = self.spawned
                self.spawned += 1
                logf = open(os.path.join(self.logdir,
                                         f"replica-{i}.log"), "w")
                cmd = [sys.executable, "-m", "hadoop_tpu.cli.main",
                       "serve",
                       "-D", "serving.kv.dfs.enable=true",
                       "-D", "serving.qos.enabled=true",
                       "-D", "serving.qos.shed.queue.depth=6",
                       # pin the overload tenants' SLO classes so the
                       # scoreboard verdict never depends on how far
                       # earlier phases' decay-shares have aged
                       "-D", "obs.slo.class.map=heavy=p3,light=p0",
                       "-D", "serving.registry.record.ttl=5s",
                       "-D", f"serving.max.batch={max_batch}",
                       "-D", f"serving.kv.block.size={block_size}",
                       "-D", f"serving.max.context={max_context}",
                       "-D", f"serving.prefill.chunk={chunk}",
                       "--name", service,
                       "--checkpoint", self.ckpt_uri,
                       "--preset", preset,
                       "--registry", f"127.0.0.1:{self.reg_port}",
                       "--host", "127.0.0.1", "--port", "0"]
                env = dict(os.environ, JAX_PLATFORMS="cpu",
                           PYTHONPATH=repo_root)
                self.procs.append((subprocess.Popen(
                    cmd, stdout=logf, stderr=subprocess.STDOUT,
                    env=env), logf))

        def scale_out(self, role, target):
            live = sum(1 for p, _ in self.procs if p.poll() is None)
            if target > live:
                self.spawn(target - live)

        def retire(self, sample, target):
            # the drained replica exits on its own; wait for it
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                for p, _ in self.procs:
                    if p.poll() is not None:
                        return
                time.sleep(0.2)

        def reap(self):
            for p, logf in self.procs:
                if p.poll() is None:
                    p.terminate()
            for p, logf in self.procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
                logf.close()

    def live_records(reg_srv):
        return [r for r in reg_srv.list(f"{REGISTRY_PREFIX}/{service}")
                if r.attributes.get("state") == "serving"]

    def wait_replicas(reg_srv, n, timeout, fleet):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            recs = live_records(reg_srv)
            if len(recs) >= n:
                return recs
            time.sleep(0.5)
        logs = ""
        for i in range(fleet.spawned):
            path = os.path.join(fleet.logdir, f"replica-{i}.log")
            if os.path.exists(path):
                with open(path) as f:
                    logs += f"\n--- replica-{i} ---\n" + f.read()[-2000:]
        raise TimeoutError(f"{n} replicas not live in {timeout}s:{logs}")

    def affinity_owner(tokens, paths):
        # the router's OWN rendezvous hash: which replica owns this
        # prompt prefix while both are alive (shared helpers — the
        # bench's owner attribution can never drift from routing)
        return rendezvous_owner(
            affinity_key(tokens, router.affinity_prefix), paths)

    failures = []
    failed_requests = [0]
    latencies_light = []
    slo_doctor = [None]
    conf = fast_conf()
    conf.set("dfs.replication", "1")
    result = {"metric": "serve_storm_peak_replicas", "unit": "replicas",
              "preset": preset, "failed": failures}
    with tempfile.TemporaryDirectory() as tmp, \
            MiniDFSCluster(num_datanodes=1, conf=conf,
                           base_dir=tmp) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        params = init_params(jax.random.PRNGKey(seed), cfg)
        save_checkpoint(fs, "/models/storm", 1,
                        {"params": params, "opt": {}})
        reg_conf = Configuration(load_defaults=False)
        reg_srv = RegistryServer(reg_conf)
        reg_srv.init(reg_conf)
        reg_srv.start()
        fleet = ProcFleet(f"{cluster.default_fs}/models/storm",
                          reg_srv.port, tmp)
        as_conf = Configuration(load_defaults=False)
        as_conf.set("serving.autoscale.interval", "1s")
        as_conf.set("serving.autoscale.ttft.p99.slo",
                    f"{slo_ttft_s:g}s")
        as_conf.set("serving.autoscale.queue.high", "1.5")
        as_conf.set("serving.autoscale.breach.polls", "2")
        as_conf.set("serving.autoscale.idle.polls", "3")
        as_conf.set("serving.autoscale.cooldown", "6s")
        as_conf.set("serving.autoscale.max", "2")
        as_conf.set("serving.autoscale.drain.timeout", "90s")
        as_conf.set("serving.registry.record.ttl", "5s")
        scaler = Autoscaler(as_conf, ("127.0.0.1", reg_srv.port),
                            service, actuator=fleet)
        router = ServingRouter(("127.0.0.1", reg_srv.port), service,
                               Configuration(load_defaults=False),
                               cache_ttl_s=0.5)
        heads = [rng.integers(0, cfg.vocab_size,
                              size=2 * block_size).tolist()
                 for _ in range(4)]
        marker_heads = [rng.integers(0, cfg.vocab_size,
                                     size=2 * block_size).tolist()
                        for _ in range(markers)]

        import random as _random
        load_rng = _random.Random(seed)   # stdlib: GIL-safe across the
        #                                   closed-loop worker threads

        def one_request(user="storm"):
            head = heads[load_rng.randrange(len(heads))]
            tail = [load_rng.randrange(cfg.vocab_size)
                    for _ in range(load_rng.randrange(2, 5))]
            try:
                router.generate({"tokens": head + tail,
                                 "max_new_tokens": max_new,
                                 "timeout": 120.0}, user=user)
            except Exception as e:  # noqa: BLE001 — ANY client-visible
                # failure breaks the zero-failures contract
                failed_requests[0] += 1
                failures.append(f"request failed: {type(e).__name__}: "
                                f"{e}")

        stop_load = threading.Event()

        def load_worker():
            while not stop_load.is_set():
                one_request()

        try:
            fleet.spawn(1)
            wait_replicas(reg_srv, 1, spawn_timeout_s, fleet)
            scaler.start()
            # phase A: light baseline
            t_phase = time.monotonic()
            while time.monotonic() - t_phase < 3.0:
                one_request()
                time.sleep(0.1)
            # phase B: the step function — closed-loop storm
            workers = [threading.Thread(target=load_worker,
                                        daemon=True)
                       for _ in range(storm_workers)]
            for w in workers:
                w.start()
            try:
                recs2 = wait_replicas(reg_srv, 2, spawn_timeout_s,
                                      fleet)
            except TimeoutError as e:
                failures.append(f"fleet never grew: {e}")
                recs2 = live_records(reg_srv)
            grow_decisions = [d for d in scaler.decisions
                              if d.action == "grow"]
            if not grow_decisions:
                failures.append("no grow decision was recorded")
            paths2 = [r.path for r in recs2]
            # settle, then judge TTFT p99 off the autoscaler's own
            # windowed signal
            time.sleep(6.0)
            p99s = []
            t_settle = time.monotonic()
            while time.monotonic() - t_settle < 5.0:
                snap = scaler.last_snapshot
                if snap is not None and snap.ttft_p99_s is not None:
                    p99s.append(snap.ttft_p99_s)
                time.sleep(0.5)
            settle_p99 = statistics.median(p99s) if p99s else None
            if settle_p99 is None:
                failures.append("no TTFT p99 signal after scale-out")
            elif settle_p99 > slo_ttft_s:
                failures.append(
                    f"settled TTFT p99 {settle_p99:.3f}s over the "
                    f"{slo_ttft_s:g}s SLO with the grown fleet")
            # phase C: calm window — seed the markers while affinity is
            # deterministic (no load imbalance), then drop the load so
            # the autoscaler scales back in
            stop_load.set()
            for w in workers:
                w.join(timeout=150.0)
            time.sleep(1.0)
            marker_owner = {}
            if len(paths2) >= 2:
                for idx, m in enumerate(marker_heads):
                    prompt = m + [1, 2]
                    marker_owner[idx] = affinity_owner(prompt, paths2)
                    try:
                        router.generate({"tokens": prompt,
                                         "max_new_tokens": 2,
                                         "timeout": 60.0})
                    except Exception as e:  # noqa: BLE001
                        failed_requests[0] += 1
                        failures.append(f"marker seed failed: {e}")
            # keep a trickle alive so drain happens under (light) load
            trickle_stop = threading.Event()

            def trickle():
                while not trickle_stop.is_set():
                    one_request()
                    time.sleep(0.4)

            tr = threading.Thread(target=trickle, daemon=True)
            tr.start()
            # scale-in complete = the victim PROCESS exited (it only
            # exits after the drain finished persisting) — the registry
            # record can expire by TTL mid-drain once heartbeats stop,
            # so record-count alone would race the persist
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                alive = sum(1 for p, _ in fleet.procs
                            if p.poll() is None)
                if alive <= 1 and len(live_records(reg_srv)) <= 1:
                    break
                time.sleep(0.5)
            trickle_stop.set()
            tr.join(timeout=150.0)
            survivors = live_records(reg_srv)
            if len(survivors) != 1:
                failures.append(f"fleet did not scale back to 1 "
                                f"(live={len(survivors)})")
            shrink_decisions = [d for d in scaler.decisions
                                if d.action == "shrink"]
            if not shrink_decisions:
                failures.append("no shrink decision was recorded")
            scaler.stop()
            # post-drain recovery: replay markers whose rendezvous
            # owner was the DRAINED replica — the survivor must map
            # them back from the DFS tier, not re-prefill
            hits_dfs_delta = 0
            try:
                result["kvcache_dirs"] = len(
                    fs.list_status("/kvcache"))
            except (OSError, IOError):
                result["kvcache_dirs"] = 0
            if survivors and marker_owner:
                surv = survivors[0]
                result["survivor"] = surv.path
                host, _, port = surv.endpoints["http"].rpartition(":")
                port = int(port)

                def surv_hits():
                    h = json.loads(http_get(host, port, "/v1/health",
                                            10.0))
                    return int(((h.get("prefix_cache") or {})
                                .get("tiers") or {}).get("hits_dfs", 0))

                before = surv_hits()
                drained_markers = [
                    i for i, owner in marker_owner.items()
                    if owner != surv.path]
                result["drained_markers"] = len(drained_markers)
                result["surv_hits_before"] = before
                if not drained_markers:
                    failures.append(
                        f"all {markers} markers rendezvous onto the "
                        f"survivor (p≈2^-{markers}) — rerun")
                for i in drained_markers:
                    status, body, _ = post_json(
                        port, "/v1/generate",
                        {"tokens": marker_heads[i] + [1, 2],
                         "max_new_tokens": 2, "timeout": 60.0})
                    if status != 200:
                        failed_requests[0] += 1
                        failures.append(
                            f"marker replay -> HTTP {status}: {body}")
                hits_dfs_delta = surv_hits() - before
                if drained_markers and hits_dfs_delta <= 0:
                    failures.append(
                        "survivor recovered nothing from the DFS tier "
                        "after the drain (hits_dfs delta 0)")
                # fleet doctor + SLO scoreboard over the overload:
                # registry-discovered, pumped synchronously (poll 1 =
                # baseline absorbing all pre-overload counters)
                from hadoop_tpu.obs.doctor import FleetDoctor
                dconf = Configuration(load_defaults=False)
                dconf.set("obs.doctor.registry",
                          f"127.0.0.1:{reg_srv.port}")
                dconf.set("obs.doctor.service",
                          f"{REGISTRY_PREFIX}/{service}")
                dconf.set("obs.doctor.push.namenode", "false")
                dconf.set("obs.doctor.interval", "3600s")
                dconf.set("obs.slo.window.fast", "2")
                dconf.set("obs.slo.window.slow", "8")
                dconf.set("obs.slo.burn.min-windows", "2")
                dconf.set("obs.slo.burn.history", "4")
                # the bench overload lasts seconds, not the hours the
                # default 14x fast gate is sized for: run the heavy
                # class on a tight error budget (99.9%) so the shed
                # storm measurably burns it, and gate at 5x so the
                # verdict is deterministic at this scenario's scale
                dconf.set("obs.slo.burn.fast", "5")
                dconf.set("obs.slo.p3.availability", "0.999")
                doctor = FleetDoctor(dconf)
                doctor.init(dconf)
                doctor.start()
                slo_doctor[0] = doctor
                doctor.poll_once()
                # QoS overload: heavy tenant floods the survivor's door
                # directly; a light tenant keeps getting served
                heavy_sheds = [0]
                light_sheds = [0]
                qos_stop = threading.Event()

                def heavy_worker():
                    while not qos_stop.is_set():
                        try:
                            status, _, ra = post_json(
                                port, "/v1/generate?user.name=heavy",
                                {"tokens": heads[0] + [3, 4],
                                 "max_new_tokens": max_new,
                                 "timeout": 60.0}, timeout=90.0)
                            if status == 429:
                                heavy_sheds[0] += 1
                                time.sleep(min(float(ra or 0.2), 0.5))
                        except OSError:
                            break

                hw = [threading.Thread(target=heavy_worker,
                                       daemon=True)
                      for _ in range(12)]
                for w in hw:
                    w.start()
                time.sleep(1.0)
                for _ in range(8):
                    t0 = time.monotonic()
                    status, body, _ = post_json(
                        port, "/v1/generate?user.name=light",
                        {"tokens": heads[1] + [5, 6],
                         "max_new_tokens": max_new,
                         "timeout": 60.0}, timeout=90.0)
                    if status == 429:
                        light_sheds[0] += 1
                    elif status != 200:
                        failures.append(
                            f"light tenant -> HTTP {status}: {body}")
                    else:
                        latencies_light.append(
                            time.monotonic() - t0)
                    time.sleep(0.2)
                qos_stop.set()
                for w in hw:
                    w.join(timeout=120.0)
                prom = http_get(host, port, "/prom", 10.0).decode()
                shed_line = [ln for ln in prom.splitlines()
                             if ln.startswith("htpu_qos_shed_total")]
                prom_sheds = sum(float(ln.rsplit(" ", 1)[1])
                                 for ln in shed_line)
                if heavy_sheds[0] <= 0 or prom_sheds <= 0:
                    failures.append(
                        f"heavy tenant was never shed under overload "
                        f"(client 429s={heavy_sheds[0]}, /prom "
                        f"sheds={prom_sheds})")
                if light_sheds[0] > 0:
                    failures.append(
                        f"light tenant was shed {light_sheds[0]} "
                        f"times — fairness inverted")
                light_p99 = (sorted(latencies_light)[
                    max(0, int(0.99 * len(latencies_light)) - 1)]
                    if latencies_light else None)
                if light_p99 is None:
                    failures.append("light tenant never completed a "
                                    "request under overload")
                elif light_p99 > qos_slo_s:
                    failures.append(
                        f"light tenant p99 {light_p99:.2f}s degraded "
                        f"past {qos_slo_s:g}s while heavy was shedding")
                # SLO scoreboard verdicts: poll 2 diffs the whole
                # overload off the baseline; poll 3's fast window still
                # spans the burn, so the min-windows hysteresis flags —
                # pure counter arithmetic, nothing sleeps or races
                doctor.poll_once()
                doctor.poll_once()
                slo_rep = json.loads(http_get(
                    "127.0.0.1", doctor.port, "/ws/v1/fleet/slo",
                    10.0))
                classes = slo_rep.get("classes") or {}
                heavy_row = classes.get("p3") or {}
                light_row = classes.get("p0") or {}
                if not heavy_row.get("burning"):
                    failures.append(
                        f"heavy class p3 never flagged burning at "
                        f"/ws/v1/fleet/slo (row: {heavy_row})")
                if light_row.get("burning"):
                    failures.append(
                        "light class p0 flagged burning — scoreboard "
                        "fairness inverted")
                light_avail = light_row.get("availability")
                if light_avail is not None and light_avail < 1.0:
                    failures.append(
                        f"light class availability {light_avail} "
                        f"under overload (contract: stays green)")
                from hadoop_tpu.obs.build import build_info
                result["slo"] = {
                    "code": build_info()["code_hash"],
                    "windows_seen": slo_rep.get("windows_seen"),
                    "classes": {
                        c: {k: row.get(k) for k in
                            ("availability", "burn_fast", "burn_slow",
                             "burning", "ttft_p99_ms",
                             "ttft_attained", "token_p99_ms", "window")}
                        for c, row in classes.items()
                        if isinstance(row, dict)}}
                result.update(
                    qos_heavy_sheds=heavy_sheds[0],
                    qos_light_sheds=light_sheds[0],
                    qos_prom_sheds=prom_sheds,
                    qos_light_p99_s=round(light_p99, 3)
                    if light_p99 is not None else None)
            if failed_requests[0] > 0:
                failures.append(
                    f"{failed_requests[0]} requests failed across the "
                    f"storm (contract: zero)")
            result.update(
                value=max(len(recs2), 1),
                grow_decisions=len(grow_decisions),
                shrink_decisions=len(shrink_decisions),
                settle_ttft_p99_s=round(settle_p99, 4)
                if settle_p99 is not None else None,
                ttft_p99_slo_s=slo_ttft_s,
                failed_requests=failed_requests[0],
                hits_dfs_delta=hits_dfs_delta,
                decisions=[{"role": d.role, "action": d.action,
                            "current": d.current, "target": d.target,
                            "reason": d.reason}
                           for d in scaler.decisions])
        finally:
            try:
                scaler.stop()
            except Exception as e:  # noqa: BLE001
                print(f"WARN: scaler stop: {e}", file=sys.stderr)
            if slo_doctor[0] is not None:
                try:
                    slo_doctor[0].stop()
                except Exception as e:  # noqa: BLE001
                    print(f"WARN: doctor stop: {e}", file=sys.stderr)
            router.close()
            fleet.reap()
            reg_srv.stop()
    return result


def run_storm_smoke() -> dict:
    """Storm smoke for benchmarks.run_all: raises unless the elastic
    contract holds end-to-end (grow → SLO held → drain-in with zero
    failures and DFS recovery → heavy-tenant shed under overload)."""
    result = run_storm()
    if result["failed"]:
        raise AssertionError("; ".join(result["failed"]))
    return result


def run_smoke() -> dict:
    """Tiny-config shared-prefix smoke for benchmarks.run_all: raises
    unless the deterministic contract holds (compile-once per shape,
    hit rate > 0, fewer engine steps with the cache). TTFT deltas ride
    along in the result for the trajectory."""
    result = run_shared_prefix(preset="tiny", requests=10, max_new=4,
                               max_batch=4, block_size=4,
                               max_context=64, chunk=8, seed=0,
                               prefix_groups=2, shared_len=24)
    if result["failed"]:
        raise AssertionError("; ".join(result["failed"]))
    return result


def run_churn_smoke() -> dict:
    """Tiny-config churn smoke for benchmarks.run_all: raises unless
    fleet hit-rate survives a replica restart via the DFS tier."""
    result = run_churn(preset="tiny")
    if result["failed"]:
        raise AssertionError("; ".join(result["failed"]))
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="tiny")
    # None = mode-dependent default: the mixed/shared-prefix modes keep
    # their historical shape; --speculate defaults to LOW occupancy
    # (batch ~2 — the regime the speculation lane targets: decode is
    # bandwidth-bound there, so verify rows are nearly free)
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--max-new", type=int, default=None)
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--block-size", type=int, default=None)
    ap.add_argument("--max-context", type=int, default=128)
    ap.add_argument("--chunk", type=int, default=16,
                    help="prefill tokens per engine step")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--shared-prefix", action="store_true",
                    help="grouped shared-head workload, run with the "
                         "prefix cache off then on; fails unless hit "
                         "rate is positive, the cache strictly reduces "
                         "engine steps, and both step shapes compile "
                         "exactly once (a wall-clock TTFT inversion is "
                         "a warning, not a failure)")
    ap.add_argument("--churn", action="store_true",
                    help="kill and restart a replica mid shared-prefix "
                         "workload over a miniDFS-backed KV store; "
                         "fails unless post-restart hit-rate is "
                         "positive (recovered from the DFS tier) with "
                         "strictly fewer engine steps than the "
                         "DFS-tier-off arm")
    ap.add_argument("--storm", action="store_true",
                    help="step-function load against a mini-fleet of "
                         "real `hadoop-tpu serve` subprocesses + the "
                         "autoscaler; fails unless the fleet grows, "
                         "TTFT p99 holds within the SLO after "
                         "scale-out settles, scale-in drains with "
                         "zero failed requests and post-drain DFS "
                         "hit-rate recovery, and a heavy tenant is "
                         "shed (429) under overload while a light "
                         "tenant keeps being served")
    ap.add_argument("--quantized", action="store_true",
                    help="weight-plane A-B: the same model served from "
                         "f32- and int8-resident weights under ONE "
                         "fixed HBM budget; fails unless the int8 arm "
                         "admits >= 2x the lanes x context (and KV "
                         "blocks), the logits A-B guard accepts the "
                         "greedy outputs, and both step shapes compile "
                         "exactly once on both arms")
    ap.add_argument("--group", type=int, default=16,
                    help="weight scale-group size (--quantized/--moe)")
    ap.add_argument("--moe", action="store_true",
                    help="MoE serving A-B: one int8-expert checkpoint "
                         "served sparse (config top_k) and dense-"
                         "compute (top_k = n_experts); fails unless "
                         "the quantized all2all payload measures >= 2x "
                         "below the f32 reference on the comm ledger, "
                         "the logits A-B guard accepts and its zeroed-"
                         "payload falsifier rejects, and both step "
                         "shapes compile exactly once on both arms")
    ap.add_argument("--longctx", action="store_true",
                    help="long-context arm (benchmarks/longctx_smoke "
                         "in an 8-virtual-device subprocess): a prompt "
                         "8x one chip's KV budget prefilled context-"
                         "parallel, KV streamed into the host/DFS "
                         "tiers, decoded through the real door with "
                         "an exact single-chip reference match, CP "
                         "guards accepted, TTFT-by-chips recorded")
    ap.add_argument("--bench-log", default="BENCH_LOG.jsonl",
                    help="trajectory log the --storm SLO scorecard "
                         "row is appended to ('' disables)")
    ap.add_argument("--prefix-groups", type=int, default=4)
    ap.add_argument("--shared-len", type=int, default=80)
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable the prefix cache (default mode only)")
    ap.add_argument("--speculate", action="store_true",
                    help="repetitive-motif workload run with "
                         "speculative decoding off then on; fails "
                         "unless greedy outputs match token-for-token, "
                         "speculation strictly reduces engine steps "
                         "with at least one accepted draft, and both "
                         "step shapes compile exactly once (a tokens/s "
                         "ratio below 1.5x is a warning, not a "
                         "failure)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens per decode lane (--speculate)")
    ap.add_argument("--motif-len", type=int, default=2,
                    help="repeated motif length (--speculate)")
    ap.add_argument("--prompt-len", type=int, default=24,
                    help="repetitive prompt length (--speculate)")
    args = ap.parse_args(argv)

    def _default(val, normal, speculate):
        if val is not None:
            return val
        return speculate if args.speculate else normal

    args.requests = _default(args.requests, 24, 8)
    args.max_new = _default(args.max_new, 32, 96)
    args.max_batch = _default(args.max_batch, 8, 2)
    args.block_size = _default(args.block_size, 16, 8)

    kw = dict(preset=args.preset, requests=args.requests,
              max_new=args.max_new, max_batch=args.max_batch,
              block_size=args.block_size, max_context=args.max_context,
              chunk=args.chunk, seed=args.seed)
    if args.speculate:
        result = run_speculate(spec_k=args.spec_k,
                               motif_len=args.motif_len,
                               prompt_len=args.prompt_len, **kw)
        failed = result["failed"]
        for msg in result["warnings"]:
            print(f"WARN: {msg}", file=sys.stderr)
    elif args.quantized:
        result = run_quantized(preset=args.preset,
                               requests=args.requests,
                               max_new=args.max_new,
                               block_size=args.block_size,
                               max_context=args.max_context,
                               chunk=args.chunk, seed=args.seed,
                               group=args.group)
        failed = result["failed"]
    elif args.moe:
        preset = args.preset if args.preset != "tiny" else "tiny-moe"
        result = run_moe(preset=preset, requests=args.requests,
                         max_new=args.max_new,
                         max_batch=args.max_batch,
                         block_size=args.block_size,
                         max_context=args.max_context,
                         chunk=args.chunk, seed=args.seed,
                         group=args.group)
        failed = result["failed"]
        for msg in result["warnings"]:
            print(f"WARN: {msg}", file=sys.stderr)
    elif args.longctx:
        from benchmarks import longctx_smoke
        result = longctx_smoke.run()
        failed = result.get("failed") or (
            [result["error"]] if "error" in result else [])
    elif args.storm:
        result = run_storm(preset=args.preset)
        failed = result["failed"]
        # the per-class SLO scorecard lands in the trajectory log so
        # fleet-level regressions between issues stay visible
        if args.bench_log and result.get("slo"):
            from benchmarks.bench_trend import append_slo_scorecard
            try:
                append_slo_scorecard(args.bench_log, result["slo"])
            except OSError as e:
                print(f"WARN: scorecard append: {e}", file=sys.stderr)
    elif args.churn:
        result = run_churn(preset=args.preset, max_new=args.max_new,
                           max_batch=args.max_batch, seed=args.seed,
                           block_size=args.block_size, chunk=args.chunk,
                           max_context=args.max_context,
                           prefix_groups=args.prefix_groups,
                           shared_len=args.shared_len)
        failed = result["failed"]
    elif args.shared_prefix:
        result = run_shared_prefix(prefix_groups=args.prefix_groups,
                                   shared_len=args.shared_len, **kw)
        failed = result["failed"]
        for msg in result["warnings"]:
            print(f"WARN: {msg}", file=sys.stderr)
    else:
        result = run(prefix_cache=not args.no_prefix_cache, **kw)
        failed = [] if result["decode_compiles"] == 1 else [
            f"step compiled {result['decode_compiles']} times "
            f"(expected exactly 1 — shape retracing crept in)"]
    for msg in failed:
        print(f"FAIL: {msg}", file=sys.stderr)
    print(json.dumps(result))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
