"""Offline serving benchmark: throughput + TTFT on synthetic traffic.

Drives the continuous-batching engine the way a replica would see load:
N requests with mixed prompt lengths submitted up front, the scheduler
admitting them into the fixed slot batch as pages free up. Reports
tokens/sec, TTFT p50/p99 (includes queue wait — the number a user
feels), mean batch occupancy, and asserts the decode step compiled
exactly once across the whole run.

Runs under JAX_PLATFORMS=cpu (tiny preset) or on real hardware with a
bigger preset. JSON output matches the BENCH_*.json shape::

    JAX_PLATFORMS=cpu python benchmarks/serve_bench.py
    python benchmarks/serve_bench.py --preset flagship-420m --requests 64
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python benchmarks/serve_bench.py` from the repo root too
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="tiny")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--max-context", type=int, default=128)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.models.decoder import count_params, init_params
    from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams
    from hadoop_tpu.serving.metrics import ServingMetrics

    cfg = get_config(args.preset)
    rng = np.random.default_rng(args.seed)
    params = init_params(jax.random.PRNGKey(args.seed), cfg)
    engine = DecodeEngine(params, cfg, max_batch=args.max_batch,
                          block_size=args.block_size,
                          max_context=min(args.max_context, cfg.max_seq),
                          metrics=ServingMetrics())
    sampling = SamplingParams(max_new_tokens=args.max_new)

    # mixed-length synthetic prompts (the realistic part of the load:
    # admission order and page pressure vary per request)
    max_prompt = max(2, engine.s_max - args.max_new - 1)
    prompts = [
        rng.integers(0, cfg.vocab_size,
                     size=int(rng.integers(2, max_prompt + 1))).tolist()
        for _ in range(args.requests)]

    # warmup: trigger both compiles outside the timed window
    engine.generate([prompts[0][:2]], SamplingParams(max_new_tokens=2))

    t0 = time.monotonic()
    reqs = [engine.submit(p, sampling) for p in prompts]
    steps0 = engine.steps
    while not all(r.done.is_set() for r in reqs):
        engine.step()
    elapsed = time.monotonic() - t0

    tokens = sum(len(r.out_tokens) for r in reqs)
    ttfts_ms = sorted((r.first_token_at - r.submitted_at) * 1e3
                      for r in reqs)

    def pct(p):
        return ttfts_ms[min(len(ttfts_ms) - 1,
                            int(p * len(ttfts_ms)))]

    occ = engine.occupancy_log
    dev = jax.devices()[0]
    result = {
        "metric": "serve_tokens_per_sec",
        "value": round(tokens / elapsed, 1),
        "unit": "tokens/s",
        "preset": args.preset,
        "n_params": count_params(params),
        "requests": args.requests,
        "max_new": args.max_new,
        "batch_slots": args.max_batch,
        "kv_block_size": args.block_size,
        "prompt_tokens": sum(len(p) for p in prompts),
        "generated_tokens": tokens,
        "elapsed_s": round(elapsed, 3),
        "decode_steps": engine.steps - steps0,
        "ttft_p50_ms": round(pct(0.50), 2),
        "ttft_p99_ms": round(pct(0.99), 2),
        "occupancy_mean": round(float(np.mean(occ)), 2) if occ else 0.0,
        "preemptions": int(engine.metrics.preemptions.value()),
        "decode_compiles": engine.decode_compiles,
        "prefill_compiles": engine.prefill_compiles,
        "device": getattr(dev, "device_kind", str(dev)),
    }
    if engine.decode_compiles != 1:
        print(f"FAIL: decode step compiled {engine.decode_compiles} "
              f"times (expected exactly 1 — shape retracing crept in)",
              file=sys.stderr)
        print(json.dumps(result))
        return 1
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
