"""End-to-end TeraSort rate: bytes/sec through the full MR-on-YARN stack.

Counterpart of the canonical reference benchmark (ref:
hadoop-mapreduce-examples/src/main/java/org/apache/hadoop/examples/
terasort/TeraSort.java + TeraGen/TeraValidate): generate N records,
sort them through map → shuffle → reduce on the minicluster, validate
global order, report sorted bytes/sec.

  python -m benchmarks.terasort_bench [--records 200000] [--nodes 3]
"""

from __future__ import annotations

import argparse
import json
import time

RECORD_LEN = 100


def run(records: int = 200_000, nodes: int = 3, reduces: int = 3,
        split_mb: int = 64) -> dict:
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.examples.terasort import (make_terasort_job, teragen,
                                              teravalidate)
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster

    # Load-tolerant intervals: the minicluster's default sub-second dead
    # detection (tuned for failover tests) misfires when dozens of task
    # processes compete for the host's cores and starve DN heartbeat
    # threads — a benchmark run is load, not failure.
    conf = Configuration(load_defaults=False)
    conf.set("dfs.heartbeat.interval", "0.5s")
    conf.set("dfs.namenode.heartbeat.recheck-interval", "5s")
    conf.set("dfs.blocksize", "64m")  # throughput sizing (not the 1 MB
    # multi-block-test default)
    from benchmarks import bench_base_dir
    base = bench_base_dir("terasort")
    cluster = MiniMRYarnCluster(num_nodes=nodes, conf=conf, base_dir=base)
    cluster.start()
    try:
        fs = cluster.get_filesystem()
        t0 = time.perf_counter()
        teragen(fs, "/tera/in", records, num_files=nodes)
        gen_dt = time.perf_counter() - t0

        job = make_terasort_job(cluster.rm_addr, cluster.default_fs,
                                "/tera/in", "/tera/out",
                                num_reduces=reduces, split_mb=split_mb)
        t0 = time.perf_counter()
        ok = job.wait_for_completion()
        sort_dt = time.perf_counter() - t0
        if not ok:
            raise RuntimeError("terasort job failed")

        checked, errors = teravalidate(fs, "/tera/out")
        if errors:
            raise RuntimeError(f"teravalidate: {errors[:3]}")
        total_bytes = records * RECORD_LEN
        return {"sort_bytes_per_sec": round(total_bytes / sort_dt, 1),
                "gen_bytes_per_sec": round(total_bytes / gen_dt, 1),
                "records": records, "validated": checked,
                "sort_seconds": round(sort_dt, 2)}
    finally:
        cluster.shutdown()
        if base:
            import shutil
            shutil.rmtree(base, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=200_000)
    ap.add_argument("--nodes", type=int, default=3)
    ap.add_argument("--reduces", type=int, default=3)
    args = ap.parse_args()
    r = run(args.records, args.nodes, args.reduces)
    print(json.dumps({
        "metric": "terasort_rate", "value": r["sort_bytes_per_sec"],
        "unit": "bytes/s", **r,
    }))


if __name__ == "__main__":
    main()
