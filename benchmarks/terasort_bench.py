"""End-to-end TeraSort rate: bytes/sec through the full MR-on-YARN stack.

Counterpart of the canonical reference benchmark (ref:
hadoop-mapreduce-examples/src/main/java/org/apache/hadoop/examples/
terasort/TeraSort.java + TeraGen/TeraValidate): generate N records,
sort them through map → shuffle → reduce on the minicluster, validate
global order, report sorted bytes/sec.

  python -m benchmarks.terasort_bench [--records 200000] [--nodes 3]
"""

from __future__ import annotations

import argparse
import json
import time

RECORD_LEN = 100


def run(records: int = 200_000, nodes: int = 3, reduces: int = 3) -> dict:
    from hadoop_tpu.examples.terasort import (make_terasort_job, teragen,
                                              teravalidate)
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster

    cluster = MiniMRYarnCluster(num_nodes=nodes)
    cluster.start()
    try:
        fs = cluster.get_filesystem()
        t0 = time.perf_counter()
        teragen(fs, "/tera/in", records, num_files=nodes)
        gen_dt = time.perf_counter() - t0

        job = make_terasort_job(cluster.rm_addr, cluster.default_fs,
                                "/tera/in", "/tera/out",
                                num_reduces=reduces)
        t0 = time.perf_counter()
        ok = job.wait_for_completion()
        sort_dt = time.perf_counter() - t0
        if not ok:
            raise RuntimeError("terasort job failed")

        checked, errors = teravalidate(fs, "/tera/out")
        if errors:
            raise RuntimeError(f"teravalidate: {errors[:3]}")
        total_bytes = records * RECORD_LEN
        return {"sort_bytes_per_sec": round(total_bytes / sort_dt, 1),
                "gen_bytes_per_sec": round(total_bytes / gen_dt, 1),
                "records": records, "validated": checked,
                "sort_seconds": round(sort_dt, 2)}
    finally:
        cluster.shutdown()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=200_000)
    ap.add_argument("--nodes", type=int, default=3)
    ap.add_argument("--reduces", type=int, default=3)
    args = ap.parse_args()
    r = run(args.records, args.nodes, args.reduces)
    print(json.dumps({
        "metric": "terasort_rate", "value": r["sort_bytes_per_sec"],
        "unit": "bytes/s", **r,
    }))


if __name__ == "__main__":
    main()
