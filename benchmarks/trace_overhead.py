"""Tracing-overhead smoke: what does the telemetry plane cost?

Two hot paths, each timed tracing-OFF (plain loop) vs tracing-ON (span
per iteration with kv annotations, metrics rate+histogram ticks, the
span collector receiving every span — the full always-on surface the
trainer/serving paths carry):

  step:  a jitted tiny-model train-ish step (forward+grad+update on
         ``models.decoder``; the trainer's own ``make_train_step`` rides
         shard_map, which this smoke deliberately avoids so the number
         is about TRACING, not about mesh plumbing)
  dfs:   writing + reading a file through a 1-DN miniDFS cluster under
         a client root span (the span context then rides the RPC and
         DataTransfer headers into the NN and DN)

The on-arm now includes the **exemplar bookkeeping** the fleet doctor
added: every histogram ``add`` under the active span captures the
sampled trace id into its bucket (one contextvar read + one tuple per
observation), so the measured overhead covers the full always-on
telemetry surface including exemplars. ``exemplars_recorded`` in the
JSON proves the path actually ran.

The recorded contract: ``step.overhead_frac`` stays under
``overhead_bound`` (5%) at the default sample rate. ``run_all`` records
a failure instead of raising, like the other smokes.

  python -m benchmarks.trace_overhead [--steps N] [--mb M]
"""

from __future__ import annotations

import argparse
import json
import time


OVERHEAD_BOUND = 0.05  # fraction of step time tracing may cost


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def bench_step(n_steps: int = 30, repeats: int = 3) -> dict:
    import jax
    import jax.numpy as jnp

    from hadoop_tpu.metrics import metrics_system
    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.models.decoder import forward, init_params
    from hadoop_tpu.tracing.collector import span_collector
    from hadoop_tpu.tracing.tracer import global_tracer

    cfg = get_config("tiny")
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 64), jnp.int32)

    def loss_fn(p):
        logits = forward(p, tokens, cfg)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    @jax.jit
    def step(p):
        g = jax.grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 1e-4 * b, p, g)

    params = jax.block_until_ready(step(params))  # compile once

    tracer = global_tracer()
    collector = span_collector()   # installed: every span is received
    reg = metrics_system().source("trace_overhead")
    rate = reg.rate("step_wall")
    hist = reg.histogram("step_wall_seconds")

    def run_off():
        p = params
        t0 = time.perf_counter()
        for _ in range(n_steps):
            p = step(p)
        jax.block_until_ready(p)
        return (time.perf_counter() - t0) / n_steps

    def run_on():
        p = params
        t0 = time.perf_counter()
        for i in range(n_steps):
            ts = time.monotonic()
            with tracer.span("trainer.step") as sp:
                sp.add_kv("step", str(i))
                p = step(p)
                # metrics recorded UNDER the span, like the serving/
                # xceiver hot paths: the histogram add auto-captures
                # the sampled trace id as its bucket exemplar — this
                # is the bookkeeping the bound now covers
                wall = time.monotonic() - ts
                rate.add(wall)
                hist.add(wall)
        jax.block_until_ready(p)
        return (time.perf_counter() - t0) / n_steps

    # interleave A/B, median-of-N: one-box noise hygiene
    offs, ons = [], []
    for _ in range(repeats):
        offs.append(run_off())
        ons.append(run_on())
    off_s, on_s = _median(offs), _median(ons)
    overhead = (on_s - off_s) / off_s if off_s > 0 else 0.0
    # exemplar bookkeeping ran on the on-arm: every add() under the
    # step span captured its sampled trace id into a bucket
    exemplars = sum(1 for e in hist.bucket_exemplars() if e is not None)
    return {
        "n_steps": n_steps,
        "repeats": repeats,
        "off_step_ms": round(off_s * 1e3, 3),
        "on_step_ms": round(on_s * 1e3, 3),
        "overhead_frac": round(overhead, 4),
        "overhead_bound": OVERHEAD_BOUND,
        "within_bound": overhead < OVERHEAD_BOUND,
        "sample_rate": tracer.sample_rate,
        "spans_collected": len(collector.snapshot()["spans"]),
        "exemplars_recorded": exemplars,
    }


def bench_comm(n_steps: int = 30, repeats: int = 3) -> dict:
    """Comm-timing arm: the SAME jitted step driven through the runtime
    comm ledger's dispatch seam (obs/comm.py) with ``obs.comm.timing``
    on vs off. Both arms pay the seam context manager (the trainer
    always enters it); the gate decides whether the per-site byte
    counters + latency histograms are bookkept — exactly the new-ledger
    cost the 5% bound must cover. The step itself has no collectives
    (single device), so a representative site profile is stamped at
    capture time: site byte values are static trace facts either way,
    and the bookkeeping cost per step is what is being measured."""
    import jax
    import jax.numpy as jnp

    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.models.decoder import forward, init_params
    from hadoop_tpu.obs.comm import comm_runtime, record_comm
    from hadoop_tpu.tracing.tracer import global_tracer

    cfg = get_config("tiny")
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 64), jnp.int32)

    def loss_fn(p):
        logits = forward(p, tokens, cfg)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    @jax.jit
    def step(p):
        g = jax.grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 1e-4 * b, p, g)

    params = jax.block_until_ready(step(params))  # compile once

    rt = comm_runtime()
    tracer = global_tracer()
    # stamp the per-step site profile the trainer's first traced step
    # would bind: one record per canonical collective site
    with rt.step("bench.comm"):
        for site in ("bucket.psum", "bucket.scatter", "zero1.gather",
                     "tp.psum", "cp.ring"):
            record_comm(site, 1 << 20, 4 << 20)

    def run(enabled: bool) -> float:
        rt.set_enabled(enabled)
        p = params
        t0 = time.perf_counter()
        for i in range(n_steps):
            with tracer.span("trainer.step"):
                with rt.step("bench.comm"):
                    p = step(p)
        jax.block_until_ready(p)
        return (time.perf_counter() - t0) / n_steps

    offs, ons = [], []
    for _ in range(repeats):
        offs.append(run(False))
        ons.append(run(True))
    rt.set_enabled(True)
    off_s, on_s = _median(offs), _median(ons)
    overhead = (on_s - off_s) / off_s if off_s > 0 else 0.0
    rep = rt.report()
    return {
        "n_steps": n_steps,
        "repeats": repeats,
        "off_step_ms": round(off_s * 1e3, 3),
        "on_step_ms": round(on_s * 1e3, 3),
        "overhead_frac": round(overhead, 4),
        "overhead_bound": OVERHEAD_BOUND,
        "within_bound": overhead < OVERHEAD_BOUND,
        "sites_observed": len(rep["sites"]),
        "payload_bytes_total": sum(
            s["payload_bytes"] for s in rep["sites"].values()),
    }


def bench_dfs(mb: int = 8, repeats: int = 3) -> dict:
    import os
    import shutil
    import tempfile

    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf
    from hadoop_tpu.tracing.tracer import global_tracer

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    conf.set("dfs.client.read.shortcircuit", "false")
    base = tempfile.mkdtemp(
        prefix="trace-overhead-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    payload = b"\xab" * (mb << 20)
    tracer = global_tracer()
    out = {"mb": mb, "repeats": repeats}
    try:
        with MiniDFSCluster(num_datanodes=1, conf=conf,
                            base_dir=base) as cluster:
            cluster.wait_active()
            fs = cluster.get_filesystem()

            def write_read(i, traced):
                path = f"/t{int(traced)}-{i}.bin"
                t0 = time.perf_counter()
                if traced:
                    with tracer.span("bench.dfs"):
                        fs.write_all(path, payload)
                        fs.read_all(path)
                else:
                    fs.write_all(path, payload)
                    fs.read_all(path)
                elapsed = time.perf_counter() - t0
                fs.delete(path)
                return elapsed

            offs = [write_read(i, False) for i in range(repeats)]
            ons = [write_read(i, True) for i in range(repeats)]
            off_s, on_s = _median(offs), _median(ons)
            out.update({
                "off_ms": round(off_s * 1e3, 2),
                "on_ms": round(on_s * 1e3, 2),
                "overhead_frac": round((on_s - off_s) / off_s, 4)
                if off_s > 0 else 0.0,
            })
    finally:
        shutil.rmtree(base, ignore_errors=True)
    return out


def run(quick: bool = False) -> dict:
    result = {"step": bench_step(n_steps=10 if quick else 30),
              "comm": bench_comm(n_steps=10 if quick else 30),
              "dfs": bench_dfs(mb=2 if quick else 8)}
    result["overhead_bound"] = OVERHEAD_BOUND
    result["within_bound"] = (result["step"]["within_bound"]
                              and result["comm"]["within_bound"])
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--mb", type=int, default=8)
    args = ap.parse_args(argv)
    result = {"step": bench_step(n_steps=args.steps),
              "comm": bench_comm(n_steps=args.steps),
              "dfs": bench_dfs(mb=args.mb),
              "overhead_bound": OVERHEAD_BOUND}
    result["within_bound"] = (result["step"]["within_bound"]
                              and result["comm"]["within_bound"])
    print(json.dumps(result, indent=2))
    return 0 if result["within_bound"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
