"""hadoop_tpu — a TPU-native distributed storage, scheduling and compute framework.

Capability-equivalent rebuild of Apache Hadoop (HDFS + YARN + MapReduce + Common)
for TPU-VM pods:

- ``hadoop_tpu.conf``      layered typed configuration    (ref: conf/Configuration.java)
- ``hadoop_tpu.service``   service lifecycle              (ref: service/AbstractService.java)
- ``hadoop_tpu.ipc``       RPC client/server with QoS     (ref: ipc/Server.java, ipc/Client.java)
- ``hadoop_tpu.io``        serialization, codecs, EC      (ref: io/)
- ``hadoop_tpu.fs``        FileSystem SPI                 (ref: fs/FileSystem.java)
- ``hadoop_tpu.dfs``       distributed filesystem         (ref: hadoop-hdfs-project)
- ``hadoop_tpu.yarn``      resource manager / node agents (ref: hadoop-yarn-project)
- ``hadoop_tpu.mr``        map/shuffle/reduce engine      (ref: hadoop-mapreduce-project)
- ``hadoop_tpu.parallel``  device meshes + ICI collectives (TPU-native data plane)
- ``hadoop_tpu.ops``       Pallas/XLA kernels (CRC, EC, sort)
- ``hadoop_tpu.metrics``   metrics registry + sinks       (ref: metrics2/)
- ``hadoop_tpu.security``  auth context / tokens seam     (ref: security/UserGroupInformation.java)

Control plane is host-side Python over DCN; bulk data rides either host streams
(storage) or XLA collectives over ICI (compute); hot host kernels are C++
(``hadoop_tpu.native``) with pure-Python fallbacks, mirroring the reference's
optional-native policy (BUILDING.txt:173-183).
"""

__version__ = "0.1.0"
