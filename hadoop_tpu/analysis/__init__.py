"""tpulint — AST static analysis for the invariants tests can only sample.

The reference Hadoop encodes its concurrency and RPC conventions
(`@GuardedBy`, FSNamesystem lock ordering, socket timeouts) as findbugs
rules run in CI; this package is the same idea for this tree, organised
as pluggable checkers over a shared parsed-module project:

``lock/*``   lock discipline: ``# guarded-by: <lock>`` field annotations
             enforced against ``with self.<lock>`` scopes, plus a
             cross-module lock-acquisition-order graph with cycle
             detection (deadlocks caught before they are scheduled).
``jit/*``    tracer discipline: inside functions reachable from
             ``jax.jit``, Python branches on traced values and host
             syncs (``.item()``, ``np.asarray``) break the engine's
             compile-once contract — flagged at the line that retraces.
``rpc/*``    RPC/retry hygiene: timeoutless sockets, ``settimeout(None)``
             on live connections, constant-sleep retry loops with no
             backoff/jitter, and silent broad ``except: pass`` swallows.
``trace/*``  tracing discipline: a ``tracer.span(...)`` that is neither
             a context manager nor guaranteed to ``finish()`` (incl.
             exception edges) never delivers — a silent hole in the
             trace someone will later debug from.
``metrics/*`` exposition discipline: one /prom family registered with
             two metric kinds (families merge across sources; the
             conflicting type is silently dropped), and prom label
             values not drawn from a bounded literal set (a label from
             request/user data mints one series per distinct value).
``parity/*`` parity-tier discipline: quantized-collective and
             chunked-matmul entry points (the relaxed plane,
             parallel/lowp) may only be reached under a lexical guard
             naming the relaxed tier, so parallel.parity=bitwise
             provably compiles byte-identical graphs.
``conf/*``   conf-lever discipline: every ``conf.get*`` site resolved
             into the generated registry (conf/registry.py) and judged
             for default drift (one key, two defaults or two typed
             getters), undocumented keys, stale doc entries, and
             near-miss typo clusters inside a namespace. The registry
             itself is gated by ``--check-conf-registry``.

Entry points: ``hadoop-tpu lint`` and ``python -m hadoop_tpu.analysis``.
Findings are suppressible per line with ``# lint: disable=<id>`` or via a
committed baseline file; the run exits nonzero on any unbaselined
finding, so tier-1 keeps the tree lint-clean.
"""

from hadoop_tpu.analysis.confcheck import ConfDisciplineChecker
from hadoop_tpu.analysis.core import (Finding, Project, SourceModule,
                                      load_baseline, run_lint)
from hadoop_tpu.analysis.jitcheck import (JitDisciplineChecker,
                                          StepBlockingChecker)
from hadoop_tpu.analysis.lockcheck import GuardedByChecker, LockOrderChecker
from hadoop_tpu.analysis.metricscheck import PromFamilyChecker
from hadoop_tpu.analysis.paritycheck import RelaxedGateChecker
from hadoop_tpu.analysis.rpccheck import (RetryHygieneChecker,
                                          SilentSwallowChecker,
                                          TimeoutChecker)
from hadoop_tpu.analysis.tracecheck import SpanFinishChecker


def all_checkers():
    """The shipped checker set, fresh instances (checkers hold state)."""
    return [GuardedByChecker(), LockOrderChecker(), JitDisciplineChecker(),
            StepBlockingChecker(), TimeoutChecker(), RetryHygieneChecker(),
            SilentSwallowChecker(), SpanFinishChecker(),
            PromFamilyChecker(), RelaxedGateChecker(),
            ConfDisciplineChecker()]


__all__ = ["Finding", "Project", "SourceModule", "run_lint",
           "load_baseline", "all_checkers", "GuardedByChecker",
           "LockOrderChecker", "JitDisciplineChecker",
           "StepBlockingChecker", "TimeoutChecker",
           "RetryHygieneChecker", "SilentSwallowChecker",
           "SpanFinishChecker", "PromFamilyChecker",
           "RelaxedGateChecker", "ConfDisciplineChecker"]
