"""``python -m hadoop_tpu.analysis`` / ``hadoop-tpu lint`` entry point.

Exit codes: 0 clean (every finding baselined or none), 1 unbaselined
findings, 2 usage error. ``--write-baseline`` records the current
findings so a later run fails only on NEW ones — the committed baseline
is meant to be burned down, never grown.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from hadoop_tpu.analysis import all_checkers
from hadoop_tpu.analysis.core import (load_baseline, run_lint,
                                      split_baselined, write_baseline)

DEFAULT_BASELINE = "LINT_BASELINE"


def _default_paths() -> List[str]:
    """The hadoop_tpu package next to this file — linting the shipped
    tree is the no-arguments behaviour."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hadoop-tpu lint",
        description="tpulint: lock discipline, jit-retracing hazards, "
                    "RPC timeout hygiene")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the hadoop_tpu "
                         "package)")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help=f"baseline file of accepted findings (default: "
                         f"./{DEFAULT_BASELINE} when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline file "
                         "and exit 0")
    ap.add_argument("--write-conf-registry", action="store_true",
                    help="regenerate hadoop_tpu/conf/registry.py and the "
                         "README conf-key appendix from the tree, then "
                         "exit 0")
    ap.add_argument("--check-conf-registry", action="store_true",
                    help="fail (exit 1) with a diff when regenerating "
                         "the conf registry would change anything — the "
                         "tier-1 drift gate")
    ap.add_argument("--checkers", metavar="IDS", default=None,
                    help="comma-separated checker names to run "
                         "(default: all)")
    ap.add_argument("--list-checkers", action="store_true",
                    help="list checker names and finding ids")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print only the summary line")
    args = ap.parse_args(argv)

    checkers = all_checkers()
    if args.list_checkers:
        for ch in checkers:
            print(f"{ch.name:16s} {', '.join(ch.ids)}")
        return 0
    if args.checkers:
        wanted = {c.strip() for c in args.checkers.split(",")}
        checkers = [c for c in checkers if c.name in wanted]
        unknown = wanted - {c.name for c in checkers}
        if unknown:
            print(f"lint: unknown checkers: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"lint: no such path: {p}", file=sys.stderr)
            return 2

    if args.write_conf_registry or args.check_conf_registry:
        from hadoop_tpu.analysis import confscan
        # root = the repo holding the (first) linted package, so the
        # registry and README land next to the tree they describe
        root = os.path.abspath(paths[0])
        if os.path.isfile(root):
            root = os.path.dirname(root)
        while os.path.isfile(os.path.join(root, "__init__.py")):
            root = os.path.dirname(root)
        if args.write_conf_registry:
            changed = confscan.write_registry(root)
            print(f"lint: conf registry "
                  + (f"updated ({', '.join(changed)})" if changed
                     else "already current"))
            return 0
        ok, diff = confscan.check_registry(root)
        if ok:
            print("lint: conf registry current")
            return 0
        for line in diff[:120]:
            print(line)
        print("lint: conf registry is STALE — run "
              "`hadoop-tpu lint --write-conf-registry` and commit")
        return 1

    # root: make finding paths stable (hadoop_tpu/... relative) wherever
    # the command runs from, matching committed baseline keys
    findings = run_lint(paths, checkers=checkers)

    if args.write_baseline:
        # write where the user pointed, else the working directory —
        # never the discovered default (a lint of /some/other/tree must
        # not clobber this repo's committed baseline)
        out = args.baseline or DEFAULT_BASELINE
        write_baseline(out, findings)
        print(f"lint: wrote {len(findings)} finding(s) to {out}")
        return 0

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        # cwd first, then the repo holding the default-linted package
        for cand in (DEFAULT_BASELINE,
                     os.path.join(os.path.dirname(_default_paths()[0]),
                                  DEFAULT_BASELINE)):
            if os.path.isfile(cand):
                baseline_path = cand
                break
    elif baseline_path is not None and not os.path.isfile(baseline_path):
        print(f"lint: baseline file not found: {baseline_path}",
              file=sys.stderr)
        return 2

    baseline = set()
    if baseline_path and not args.no_baseline:
        baseline = load_baseline(baseline_path)
    new, old = split_baselined(findings, baseline)

    if not args.quiet:
        for f in new:
            print(f.render())
    n_files = len({f.path for f in new})
    if new:
        print(f"lint: {len(new)} unbaselined finding(s) in {n_files} "
              f"file(s)" + (f" ({len(old)} baselined)" if old else ""))
        return 1
    print(f"lint: clean ({len(old)} baselined finding(s))"
          if old else "lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
