"""Conf-lever discipline — the registry's four checkers.

``conf/default-drift`` — one key, two truths. The same conf key read
with different resolved defaults in different files means the fleet's
effective default depends on which module reads first; the same key
read through conflicting typed getters (``get_list`` here, ``get``
there) means the two sites disagree about the value's shape. Both are
the exact bug class the reference centralises ``DFSConfigKeys`` to
prevent. Fix by single-sourcing the key and default in
``hadoop_tpu/conf/keys.py``.

``conf/undocumented-key`` — a key read in code but absent from
README.md (generated appendix included). Every lever an operator can
set must be documented; ``hadoop-tpu lint --write-conf-registry``
regenerates the appendix so the fix is mechanical.

``conf/stale-doc-key`` — a key documented in a marked README conf
table (``<!-- conf-keys:begin -->`` blocks and the generated appendix)
that no code reads. Stale docs send operators chasing knobs that do
nothing — usually a typo'd or renamed key.

``conf/typo-cluster`` — near-miss key names inside one registered
namespace: same parent with leaf edit distance 1
(``...data.dir`` / ``...data.dirs``), or whole-key equality after
separator normalisation (``store-dir`` / ``store.dir``). One of the
pair is a typo of the other; readers of each see half the
configuration.

All four run in ``finalize`` over the shared ``confscan`` extraction,
so a fixture tree and the shipped tree are judged identically.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.analysis.core import (Checker, Finding, Project,
                                      SourceModule)
from hadoop_tpu.analysis.confscan import (ABSENT, DYNAMIC, ConfRead,
                                          doc_covers, readme_doc_keys,
                                          scan_project)


def _edit1(a: str, b: str) -> bool:
    """Levenshtein distance exactly 1 (one insert/delete/substitute)."""
    if a == b:
        return False
    la, lb = len(a), len(b)
    if abs(la - lb) > 1:
        return False
    if la > lb:
        a, b, la, lb = b, a, lb, la
    i = j = diffs = 0
    while i < la and j < lb:
        if a[i] == b[j]:
            i += 1
            j += 1
            continue
        diffs += 1
        if diffs > 1:
            return False
        if la == lb:
            i += 1
            j += 1
        else:
            j += 1
    return True


def _split_key(key: str) -> Tuple[str, str]:
    return key.rsplit(".", 1) if "." in key else ("", key)


class ConfDisciplineChecker(Checker):
    name = "conf"
    ids = ("conf/default-drift", "conf/undocumented-key",
           "conf/stale-doc-key", "conf/typo-cluster")

    def finalize(self, project: Project) -> List[Finding]:
        if not project.modules:
            return []
        scan = scan_project(project)
        by_rel: Dict[str, SourceModule] = {m.rel: m for m in
                                           project.modules}
        readme = self._readme(project)
        findings: List[Finding] = []

        concrete: Dict[str, List[ConfRead]] = {}
        patterns: Dict[str, List[ConfRead]] = {}
        for r in scan.reads:
            (patterns if r.is_pattern else concrete).setdefault(
                r.key, []).append(r)
        for reads in concrete.values():
            reads.sort(key=lambda r: (r.rel, r.line))
        for reads in patterns.values():
            reads.sort(key=lambda r: (r.rel, r.line))

        self._check_drift(concrete, by_rel, findings)
        self._check_typos(concrete, by_rel, findings)
        if readme is not None:
            self._check_docs(concrete, patterns, readme, by_rel, findings)
        return findings

    # ----------------------------------------------------------- readme

    @staticmethod
    def _readme(project: Project) -> Optional[Tuple[str, str]]:
        """(rel path, text) of the lint root's README, when present."""
        mod = project.modules[0]
        suffix = mod.rel.replace("/", os.sep)
        if not mod.path.endswith(suffix):
            return None
        root = mod.path[:-len(suffix)]
        path = os.path.join(root, "README.md")
        if not os.path.isfile(path):
            return None
        with open(path, "r", encoding="utf-8") as fh:
            return ("README.md", fh.read())

    # ------------------------------------------------------------ drift

    def _check_drift(self, concrete: Dict[str, List[ConfRead]],
                     by_rel: Dict[str, SourceModule],
                     findings: List[Finding]) -> None:
        for key, reads in sorted(concrete.items()):
            resolved = [r for r in reads
                        if r.defaults not in ((ABSENT,), (DYNAMIC,))]
            if len({r.defaults for r in resolved}) > 1:
                first = resolved[0]
                for r in resolved[1:]:
                    if r.defaults == first.defaults:
                        continue
                    self._emit(
                        by_rel, r, "conf/default-drift",
                        f"conf key '{key}' read with default "
                        f"{', '.join(r.defaults)} here but "
                        f"{', '.join(first.defaults)} at "
                        f"{first.rel}:{first.line} — the effective "
                        f"default depends on which module reads first; "
                        f"single-source it in hadoop_tpu/conf/keys.py",
                        findings)
            if len({r.rtype for r in reads}) > 1:
                first = reads[0]
                for r in reads[1:]:
                    if r.rtype == first.rtype:
                        continue
                    self._emit(
                        by_rel, r, "conf/default-drift",
                        f"conf key '{key}' read as {r.rtype} here but as "
                        f"{first.rtype} at {first.rel}:{first.line} — "
                        f"the two sites disagree about the value's shape",
                        findings)

    # ------------------------------------------------------------ typos

    def _check_typos(self, concrete: Dict[str, List[ConfRead]],
                     by_rel: Dict[str, SourceModule],
                     findings: List[Finding]) -> None:
        keys = sorted(concrete)
        for i, a in enumerate(keys):
            pa, la = _split_key(a)
            for b in keys[i + 1:]:
                pb, lb = _split_key(b)
                near = (pa == pb and _edit1(la, lb)) or \
                    (a.replace("-", ".") == b.replace("-", "."))
                if not near:
                    continue
                # flag the rarer spelling — it is usually the typo
                # (ties: the lexicographically later one)
                fa, fb = len(concrete[a]), len(concrete[b])
                if fa != fb:
                    victim, other = (a, b) if fa < fb else (b, a)
                else:
                    victim, other = (b, a) if a < b else (a, b)
                site = concrete[victim][0]
                o = concrete[other][0]
                self._emit(
                    by_rel, site, "conf/typo-cluster",
                    f"conf key '{victim}' is a near-miss of '{other}' "
                    f"(read at {o.rel}:{o.line}) — writers of one are "
                    f"invisible to readers of the other; unify the "
                    f"spelling (a DeprecationDelta keeps old setters "
                    f"working)", findings)

    # ------------------------------------------------------------- docs

    def _check_docs(self, concrete: Dict[str, List[ConfRead]],
                    patterns: Dict[str, List[ConfRead]],
                    readme: Tuple[str, str],
                    by_rel: Dict[str, SourceModule],
                    findings: List[Finding]) -> None:
        rel, text = readme
        docs = readme_doc_keys(text)
        all_docs = set(docs)
        for key in sorted(set(concrete) | set(patterns)):
            if doc_covers(all_docs, key):
                continue
            site = (concrete.get(key) or patterns[key])[0]
            self._emit(
                by_rel, site, "conf/undocumented-key",
                f"conf key '{key}' is read here but documented nowhere "
                f"in {rel} — every operator-settable lever must be "
                f"documented (hadoop-tpu lint --write-conf-registry "
                f"regenerates the appendix)", findings)
        roots = {k.split(".", 1)[0] for k in concrete} | \
                {k.split(".", 1)[0] for k in patterns}
        roots.discard("*")
        registered = set(concrete) | set(patterns)
        for tok in sorted(docs):
            line, in_gen, in_doc = docs[tok]
            if not (in_gen or in_doc):
                continue          # prose mention, not a conf table row
            if tok.split(".", 1)[0] not in roots:
                continue
            if doc_covers(registered, tok):
                continue
            findings.append(Finding(
                rel, line, "conf/stale-doc-key",
                f"documented conf key '{tok}' is read nowhere in the "
                f"tree — a stale or typo'd doc entry sends operators "
                f"chasing a knob that does nothing"))

    # ------------------------------------------------------------ emit

    @staticmethod
    def _emit(by_rel: Dict[str, SourceModule], read: ConfRead,
              checker: str, message: str,
              findings: List[Finding]) -> None:
        mod = by_rel.get(read.rel)
        if mod is None:
            findings.append(Finding(read.rel, read.line, checker, message))
            return
        f = mod.finding(read.line, checker, message)
        if f is not None:
            findings.append(f)
