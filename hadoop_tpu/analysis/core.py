"""Checker plumbing: parsed modules, suppressions, baseline, runner.

Mirrors how findbugs runs in the reference's CI: every checker sees every
module (so cross-module facts like the lock-order graph accumulate), then
a finalize pass emits whole-project findings. Suppression is per line
(``# lint: disable=<id>``), per file (``# lint: disable-file=<id>`` in the
header), or via a committed baseline of ``path:line:checker`` keys that is
meant to be burned down, never grown.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_DISABLE_RE = re.compile(r"#\s*lint:\s*disable=([\w/,\- ]+)")
_DISABLE_FILE_RE = re.compile(r"#\s*lint:\s*disable-file=([\w/,\- ]+)")
_HOLDS_RE = re.compile(r"#\s*lint:\s*holds=([\w,\- ]+)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([\w.]+)")
_STATIC_FN_RE = re.compile(r"#\s*lint:\s*static-fn")


class Finding:
    """One diagnostic: a checker id anchored to a file:line."""

    __slots__ = ("path", "line", "checker", "message")

    def __init__(self, path: str, line: int, checker: str, message: str):
        self.path = path          # posix-relative to the lint root
        self.line = line
        self.checker = checker    # e.g. "lock/guarded-by"
        self.message = message

    def key(self) -> str:
        return f"{self.path}:{self.line}:{self.checker}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Finding {self.render()}>"


class SourceModule:
    """One parsed file plus the line-comment annotations checkers read."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of suppressed checker ids ("all" suppresses any)
        self.suppressed: Dict[int, Set[str]] = {}
        self.file_suppressed: Set[str] = set()
        # def-line -> lock names the function body is documented to hold
        self.holds: Dict[int, Set[str]] = {}
        # line -> guard annotation (field assignments name their lock)
        self.guards: Dict[int, str] = {}
        # def lines marked "# lint: static-fn": the function returns
        # trace-time-static metadata (shapes, axis sets), so its result
        # never taints jit-discipline analysis
        self.static_fn_lines: Set[int] = set()
        for i, text in enumerate(self.lines, start=1):
            if "#" not in text:
                continue
            m = _DISABLE_RE.search(text)
            if m:
                ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
                self.suppressed.setdefault(i, set()).update(ids)
            m = _DISABLE_FILE_RE.search(text)
            if m and i <= 10:
                self.file_suppressed.update(
                    s.strip() for s in m.group(1).split(",") if s.strip())
            m = _HOLDS_RE.search(text)
            if m:
                self.holds[i] = {s.strip() for s in m.group(1).split(",")
                                 if s.strip()}
            m = _GUARDED_RE.search(text)
            if m:
                self.guards[i] = m.group(1).strip()
            if _STATIC_FN_RE.search(text):
                self.static_fn_lines.add(i)

    # dotted module name under the package root, e.g. "hadoop_tpu.ipc.client"
    @property
    def dotted(self) -> str:
        stem = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        parts = stem.split("/")
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    def is_suppressed(self, line: int, checker: str) -> bool:
        if checker in self.file_suppressed or "all" in self.file_suppressed:
            return True
        ids = self.suppressed.get(line)
        return bool(ids) and (checker in ids or "all" in ids)

    def finding(self, node_or_line, checker: str,
                message: str) -> Optional[Finding]:
        """Build a Finding unless that line suppresses the checker."""
        line = getattr(node_or_line, "lineno", node_or_line)
        if self.is_suppressed(line, checker):
            return None
        return Finding(self.rel, line, checker, message)


class Project:
    """Every module the run will see; shared context for finalize()."""

    def __init__(self, modules: Sequence[SourceModule]):
        self.modules = list(modules)
        self.by_dotted: Dict[str, SourceModule] = {
            m.dotted: m for m in self.modules}


class Checker:
    """Base checker. ``check_module`` runs per file (and may accumulate
    project-wide state); ``finalize`` emits cross-module findings."""

    name = "checker"
    ids: Tuple[str, ...] = ()

    def check_module(self, mod: SourceModule) -> List[Finding]:
        return []

    def finalize(self, project: Project) -> List[Finding]:
        return []


# --------------------------------------------------------------- discovery

_EXCLUDE_DIRS = {"__pycache__", ".git", "node_modules", ".venv"}


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(os.path.abspath(p))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _EXCLUDE_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.abspath(os.path.join(dirpath, fn)))
    return out


def load_project(paths: Iterable[str], root: Optional[str] = None
                 ) -> Tuple[Project, List[Finding]]:
    """Parse every .py under ``paths``. Unparseable files become findings
    (a lint run must not die on one bad file)."""
    files = iter_py_files(paths)
    if root is None:
        root = os.path.commonpath(files) if files else os.getcwd()
    root = os.path.abspath(root)
    if os.path.isfile(root):  # single-file run
        root = os.path.dirname(root)
    # walk out of the package so rel paths (and dotted names) carry the
    # package prefix: hadoop_tpu/ipc/client.py, not ipc/client.py
    while os.path.isfile(os.path.join(root, "__init__.py")):
        root = os.path.dirname(root)
    modules: List[SourceModule] = []
    errors: List[Finding] = []
    for f in files:
        rel = os.path.relpath(f, root)
        try:
            with open(f, "r", encoding="utf-8") as fh:
                src = fh.read()
            modules.append(SourceModule(f, rel, src))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            line = getattr(e, "lineno", None) or 1
            errors.append(Finding(rel.replace(os.sep, "/"), line,
                                  "parse/error", f"cannot analyse: {e}"))
    return Project(modules), errors


def run_lint(paths: Iterable[str], checkers=None,
             root: Optional[str] = None) -> List[Finding]:
    """Run ``checkers`` (default: the shipped set) over ``paths``."""
    if checkers is None:
        from hadoop_tpu.analysis import all_checkers
        checkers = all_checkers()
    project, findings = load_project(paths, root=root)
    for mod in project.modules:
        for ch in checkers:
            findings.extend(ch.check_module(mod))
    for ch in checkers:
        findings.extend(ch.finalize(project))
    uniq = {}
    for f in findings:
        uniq.setdefault(f.key(), f)
    findings = list(uniq.values())
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings


# ---------------------------------------------------------------- baseline

def load_baseline(path: str) -> Set[str]:
    """Baseline lines are finding keys (``path:line:checker``); ``#``
    starts a comment (used to justify each kept entry)."""
    keys: Set[str] = set()
    with open(path, "r", encoding="utf-8") as fh:
        for raw in fh:
            line = raw.split("#", 1)[0].strip()
            if line:
                keys.add(line)
    return keys


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# tpulint baseline — burn down, never grow. Each entry\n"
                 "# is path:line:checker and should carry a justification.\n")
        for f in findings:
            fh.write(f"{f.key()}  # {f.message}\n")


def split_baselined(findings: Sequence[Finding], baseline: Set[str]
                    ) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined) — matching is exact on path:line:checker."""
    new, old = [], []
    for f in findings:
        (old if f.key() in baseline else new).append(f)
    return new, old


# -------------------------------------------------------------- AST helpers

def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` -> ["a", "b", "c"]; None for non-name-rooted chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """Dotted name of the called object, when name-rooted."""
    chain = attr_chain(node.func)
    return ".".join(chain) if chain else None
