"""Tracer discipline: protect the compile-once contract.

T3 (arxiv 2401.16677) and Flash Communication (arxiv 2412.04964) both
show overlap/fusion wins evaporating when a stray host sync or retrace
lands on the hot path. Inside any function reachable from ``jax.jit``
this checker flags, by value-taint from the jitted function's traced
parameters:

``jit/traced-branch``  Python ``if``/``while``/``for`` control flow on a
                       traced VALUE (``x.shape``-derived quantities are
                       static and stay exempt, as do ``is None`` checks —
                       both are legal trace-time Python). Each distinct
                       branch path is a separate compiled program: a
                       retrace per step on the serving hot path.
``jit/host-sync``      ``.item()``/``.tolist()``/``float()``/``int()`` /
                       ``np.asarray()``/``device_get`` on a traced value —
                       a device round-trip (TracerConversionError at best,
                       a silent pipeline bubble at worst).

Roots are found per module: ``jax.jit(f)`` / ``@jax.jit`` /
``@partial(jax.jit, ...)``, unwrapping ``shard_map``/``partial`` wrappers
and following assignments (``prog = jax.jit(shard_map(body, ...))``).
Reachability follows same-module calls, ``self.`` methods, and
``from hadoop_tpu.x import f`` imports, mapping argument taint onto
callee parameters (so a constant-table default argument stays static).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from hadoop_tpu.analysis.core import (Checker, Finding, Project,
                                      SourceModule, attr_chain, call_name)

# attribute reads that yield STATIC (trace-time Python) values
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding"}
# callables whose result is static regardless of argument taint.
# NOT here: range/max/min/enumerate/zip — those propagate their
# arguments' taint (range(n) over a traced n is a traced trip count),
# which the generic Call handling already models. len() is static: it
# reads the leading shape dimension.
_STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr",
                 "type", "str", "repr",
                 "jnp.issubdtype", "jnp.iinfo", "jnp.finfo", "np.iinfo",
                 "np.finfo"}
# receivers of a method call that sync the device when the value is traced
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
               "jax.device_get", "device_get", "onp.asarray", "onp.array"}
_SYNC_CASTS = {"float", "int", "bool", "complex"}


class _FuncDef:
    def __init__(self, mod: SourceModule, node, cls: Optional[str]):
        self.mod = mod
        self.node = node
        self.cls = cls
        self.name = getattr(node, "name", f"<lambda:{node.lineno}>")
        self.qual = (f"{mod.dotted}.{cls}.{self.name}" if cls
                     else f"{mod.dotted}.{self.name}")


class JitDisciplineChecker(Checker):
    name = "jit-discipline"
    ids = ("jit/traced-branch", "jit/host-sync")

    def __init__(self):
        # qual -> _FuncDef for every def in the project
        self._defs: Dict[str, _FuncDef] = {}
        # import maps per module: local name -> qualified target
        self._imports: Dict[str, Dict[str, str]] = {}
        # jit roots: (qual, params statically bound by partial/defaults)
        self._roots: List[Tuple[str, frozenset]] = []
        # defs marked "# lint: static-fn" (trace-time metadata helpers)
        self._static_fns: Set[str] = set()

    # ------------------------------------------------------- collection

    def check_module(self, mod: SourceModule) -> List[Finding]:
        imports: Dict[str, str] = {}
        self._imports[mod.dotted] = imports
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module and \
                    node.level == 0:
                for alias in node.names:
                    imports[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"
        self._index_defs(mod, mod.tree.body, cls=None)
        self._find_roots(mod)
        return []

    def _index_defs(self, mod: SourceModule, body, cls: Optional[str]):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fd = _FuncDef(mod, node, cls)
                self._defs[fd.qual] = fd
                if node.lineno in mod.static_fn_lines:
                    self._static_fns.add(fd.qual)
                # nested defs are reachable via their enclosing scope;
                # index them under the same class for self-resolution
                self._index_defs(mod, node.body, cls)
            elif isinstance(node, ast.ClassDef):
                self._index_defs(mod, node.body, cls=node.name)

    def _find_roots(self, mod: SourceModule) -> None:
        # decorators
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_jit_expr(dec):
                        self._roots.append((self._qual_for(mod, node),
                                            frozenset()))
            if isinstance(node, ast.Call) and self._is_jit_call(node):
                if node.args:
                    target = self._unwrap(mod, node.args[0])
                    if target:
                        self._roots.append(target)

    def _qual_for(self, mod: SourceModule, node) -> str:
        for q, fd in self._defs.items():
            if fd.node is node:
                return q
        return f"{mod.dotted}.{node.name}"

    @staticmethod
    def _is_jit_call(node: ast.Call) -> bool:
        name = call_name(node)
        return name in ("jax.jit", "jit")

    def _is_jit_expr(self, dec: ast.AST) -> bool:
        chain = attr_chain(dec)
        if chain and ".".join(chain) in ("jax.jit", "jit"):
            return True
        if isinstance(dec, ast.Call):
            name = call_name(dec)
            if name in ("jax.jit", "jit"):
                return True
            if name in ("partial", "functools.partial") and dec.args:
                return self._is_jit_expr(dec.args[0])
        return False

    def _unwrap(self, mod: SourceModule, expr: ast.AST, depth: int = 0
                ) -> Optional[Tuple[str, frozenset]]:
        """Resolve the function object inside jax.jit(...): through
        shard_map/partial wrappers, local assignments, lambdas, and
        self-attributes, to (qual, statically-bound-params). Params
        bound by ``partial`` are Python constants at jit-wrap time, so
        they never carry tracers."""
        if depth > 6:
            return None
        if isinstance(expr, ast.Lambda):
            # register the lambda itself as an analysable def: its
            # defaulted params (constant tables) stay static, its
            # call-time params are traced
            fd = _FuncDef(mod, expr, cls=None)
            self._defs.setdefault(fd.qual, fd)
            return (fd.qual, frozenset())
        chain = attr_chain(expr)
        if chain:
            if chain[0] == "self" and len(chain) == 2:
                for q, fd in self._defs.items():
                    if fd.mod is mod and fd.cls and fd.name == chain[1]:
                        return (q, frozenset())
                return None
            dotted = ".".join(chain)
            local = f"{mod.dotted}.{dotted}"
            if local in self._defs:
                return (local, frozenset())
            imported = self._imports.get(mod.dotted, {}).get(dotted)
            if imported in self._defs:
                return (imported, frozenset())
            # a local variable: find its assignment and unwrap the value
            if len(chain) == 1:
                for node in ast.walk(mod.tree):
                    if isinstance(node, ast.Assign):
                        for t in node.targets:
                            if isinstance(t, ast.Name) and \
                                    t.id == chain[0]:
                                got = self._unwrap(mod, node.value,
                                                   depth + 1)
                                if got:
                                    return got
            return None
        if isinstance(expr, ast.Call):
            name = call_name(expr)
            if name in ("shard_map", "_smap", "jax.jit", "jit",
                        "checkpoint", "jax.checkpoint", "remat",
                        "jax.remat", "vmap", "jax.vmap"):
                if expr.args:
                    return self._unwrap(mod, expr.args[0], depth + 1)
            if name in ("partial", "functools.partial") and expr.args:
                got = self._unwrap(mod, expr.args[0], depth + 1)
                if got is None:
                    return None
                qual, static = got
                fd = self._defs.get(qual)
                if fd is None:
                    return got
                params = [a.arg for a in fd.node.args.args
                          if a.arg != "self"]
                bound = set(static)
                # positional partial args bind leading params
                bound.update(params[:len(expr.args) - 1])
                # keyword partial args bind by name
                bound.update(k.arg for k in expr.keywords if k.arg)
                return (qual, frozenset(bound))
        return None

    # -------------------------------------------------------- finalize

    def finalize(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        # worklist of (qual, frozenset tainted param names)
        seen: Set[Tuple[str, frozenset]] = set()
        work: List[Tuple[str, frozenset]] = []
        for root, static in self._roots:
            fd = self._defs.get(root)
            if fd is None:
                continue
            tainted = frozenset(self._root_tainted_params(fd) - static)
            work.append((root, tainted))
        while work:
            qual, tainted = work.pop()
            if (qual, tainted) in seen:
                continue
            seen.add((qual, tainted))
            fd = self._defs.get(qual)
            if fd is None:
                continue
            calls = self._analyse(fd, set(tainted), findings)
            for callee, callee_tainted in calls:
                work.append((callee, frozenset(callee_tainted)))
        # dedupe (same function may be analysed under several taint sets)
        uniq: Dict[str, Finding] = {}
        for f in findings:
            uniq.setdefault(f.key(), f)
        return list(uniq.values())

    @staticmethod
    def _root_tainted_params(fd: _FuncDef) -> Set[str]:
        """Positional params without defaults are traced; ``self`` and
        defaulted params (constant tables bound at jit time) are not."""
        args = fd.node.args
        n_default = len(args.defaults)
        names = [a.arg for a in args.args]
        cut = len(names) - n_default if n_default else len(names)
        return {n for n in names[:cut] if n != "self"}

    # ---- per-function taint pass

    def _analyse(self, fd: _FuncDef, tainted: Set[str],
                 findings: List[Finding]
                 ) -> List[Tuple[str, Set[str]]]:
        mod = fd.mod
        out_calls: List[Tuple[str, Set[str]]] = []

        def expr_tainted(e: ast.AST) -> bool:
            if isinstance(e, ast.Name):
                return e.id in tainted
            if isinstance(e, ast.Attribute):
                if e.attr in _STATIC_ATTRS:
                    return False
                return expr_tainted(e.value)
            if isinstance(e, ast.Subscript):
                return expr_tainted(e.value) or expr_tainted(e.slice)
            if isinstance(e, ast.Call):
                name = call_name(e)
                if name in _STATIC_CALLS:
                    return False
                resolved = self._resolve_call(fd, e)
                if resolved is not None and resolved in self._static_fns:
                    return False  # marked "# lint: static-fn"
                if name and (name.split(".")[-1] in
                             ("astype", "reshape", "sum", "mean", "get")):
                    return expr_tainted(e.func)
                args_tainted = any(expr_tainted(a) for a in e.args) or \
                    any(expr_tainted(k.value) for k in e.keywords)
                if isinstance(e.func, ast.Attribute):
                    return args_tainted or expr_tainted(e.func.value)
                return args_tainted
            if isinstance(e, ast.BinOp):
                return expr_tainted(e.left) or expr_tainted(e.right)
            if isinstance(e, ast.UnaryOp):
                return expr_tainted(e.operand)
            if isinstance(e, ast.BoolOp):
                return any(expr_tainted(v) for v in e.values)
            if isinstance(e, ast.Compare):
                # `x is None` / `x is not None` is trace-time Python
                if all(isinstance(op, (ast.Is, ast.IsNot))
                       for op in e.ops):
                    return False
                return expr_tainted(e.left) or \
                    any(expr_tainted(c) for c in e.comparators)
            if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
                return any(expr_tainted(el) for el in e.elts)
            if isinstance(e, ast.IfExp):
                return (expr_tainted(e.test) or expr_tainted(e.body)
                        or expr_tainted(e.orelse))
            if isinstance(e, ast.Starred):
                return expr_tainted(e.value)
            return False

        def taint_targets(t: ast.AST) -> List[str]:
            if isinstance(t, ast.Name):
                return [t.id]
            if isinstance(t, (ast.Tuple, ast.List)):
                out = []
                for el in t.elts:
                    out.extend(taint_targets(el))
                return out
            return []

        # two passes so taint flowing backwards through loops settles
        body = fd.node.body
        for _ in range(2):
            for stmt in ast.walk(fd.node):
                if isinstance(stmt, ast.Assign) and \
                        expr_tainted(stmt.value):
                    for t in stmt.targets:
                        tainted.update(taint_targets(t))
                elif isinstance(stmt, ast.AugAssign) and \
                        (expr_tainted(stmt.value) or
                         expr_tainted(stmt.target)):
                    tainted.update(taint_targets(stmt.target))
                elif isinstance(stmt, (ast.For, ast.AsyncFor)) and \
                        expr_tainted(stmt.iter):
                    tainted.update(taint_targets(stmt.target))

        # findings + call propagation
        for node in ast.walk(fd.node):
            if isinstance(node, (ast.If, ast.While)):
                if expr_tainted(node.test):
                    f = mod.finding(
                        node, "jit/traced-branch",
                        f"Python branch on a traced value inside "
                        f"jit-reachable {fd.name}() — every distinct "
                        f"outcome is a retrace (use jnp.where/lax.cond)")
                    if f:
                        findings.append(f)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if expr_tainted(node.iter):
                    f = mod.finding(
                        node, "jit/traced-branch",
                        f"Python loop over a traced value inside "
                        f"jit-reachable {fd.name}() — trip count "
                        f"must be static (use lax.scan/fori_loop)")
                    if f:
                        findings.append(f)
            elif isinstance(node, ast.Call):
                self._check_sync(fd, node, expr_tainted, findings)
                callee = self._resolve_call(fd, node)
                if callee:
                    callee_tainted = self._map_args(callee, node,
                                                    expr_tainted)
                    if callee_tainted is not None:
                        out_calls.append((callee, callee_tainted))
        return out_calls

    def _check_sync(self, fd: _FuncDef, node: ast.Call, expr_tainted,
                    findings: List[Finding]) -> None:
        mod = fd.mod
        name = call_name(node)
        msg = None
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SYNC_METHODS and \
                expr_tainted(node.func.value):
            msg = (f".{node.func.attr}() on a traced value inside "
                   f"jit-reachable {fd.name}() forces a host sync")
        elif name in _SYNC_CALLS and any(expr_tainted(a)
                                         for a in node.args):
            msg = (f"{name}() materialises a traced value on the host "
                   f"inside jit-reachable {fd.name}()")
        elif name in _SYNC_CASTS and len(node.args) == 1 and \
                expr_tainted(node.args[0]):
            msg = (f"{name}() on a traced value inside jit-reachable "
                   f"{fd.name}() forces a host sync "
                   f"(use jnp casts / keep it on device)")
        if msg:
            f = mod.finding(node, "jit/host-sync", msg)
            if f:
                findings.append(f)

    def _resolve_call(self, fd: _FuncDef, node: ast.Call) -> Optional[str]:
        chain = attr_chain(node.func)
        if not chain:
            return None
        if chain[0] == "self" and len(chain) == 2 and fd.cls:
            q = f"{fd.mod.dotted}.{fd.cls}.{chain[1]}"
            return q if q in self._defs else None
        dotted = ".".join(chain)
        local = f"{fd.mod.dotted}.{dotted}"
        if local in self._defs:
            return local
        # same-class nested / sibling functions indexed under the class
        if fd.cls and len(chain) == 1:
            q = f"{fd.mod.dotted}.{fd.cls}.{chain[0]}"
            if q in self._defs:
                return q
        imported = self._imports.get(fd.mod.dotted, {}).get(dotted)
        if imported in self._defs:
            return imported
        return None

    def _map_args(self, callee_qual: str, call: ast.Call,
                  expr_tainted) -> Optional[Set[str]]:
        """Taint callee params fed by tainted arguments (positional and
        keyword); returns None when nothing tainted flows in."""
        callee = self._defs[callee_qual]
        params = [a.arg for a in callee.node.args.args]
        if params and params[0] == "self":
            params = params[1:]
        tainted: Set[str] = set()
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                if expr_tainted(arg.value):
                    tainted.update(params[i:])
                break
            if i < len(params) and expr_tainted(arg):
                tainted.add(params[i])
        for kw in call.keywords:
            if kw.arg and kw.arg in [a.arg for a in callee.node.args.args] \
                    and expr_tainted(kw.value):
                tainted.add(kw.arg)
        return tainted if tainted else None
