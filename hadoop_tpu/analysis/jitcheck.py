"""Tracer discipline: protect the compile-once contract.

T3 (arxiv 2401.16677) and Flash Communication (arxiv 2412.04964) both
show overlap/fusion wins evaporating when a stray host sync or retrace
lands on the hot path. Inside any function reachable from ``jax.jit``
this checker flags, by value-taint from the jitted function's traced
parameters:

``jit/traced-branch``  Python ``if``/``while``/``for`` control flow on a
                       traced VALUE (``x.shape``-derived quantities are
                       static and stay exempt, as do ``is None`` checks —
                       both are legal trace-time Python). Each distinct
                       branch path is a separate compiled program: a
                       retrace per step on the serving hot path.
``jit/host-sync``      ``.item()``/``.tolist()``/``float()``/``int()`` /
                       ``np.asarray()``/``device_get`` on a traced value —
                       a device round-trip (TracerConversionError at best,
                       a silent pipeline bubble at worst).

Roots are found per module: ``jax.jit(f)`` / ``@jax.jit`` /
``@partial(jax.jit, ...)``, unwrapping ``shard_map``/``partial`` wrappers
and following assignments (``prog = jax.jit(shard_map(body, ...))``).
Reachability follows same-module calls, ``self.`` methods, and
``from hadoop_tpu.x import f`` imports, mapping argument taint onto
callee parameters (so a constant-table default argument stays static).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from hadoop_tpu.analysis.core import (Checker, Finding, Project,
                                      SourceModule, attr_chain, call_name)

# attribute reads that yield STATIC (trace-time Python) values
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding",
                 "itemsize"}
# callables whose result is static regardless of argument taint.
# NOT here: range/max/min — those propagate their arguments' taint
# (range(n) over a traced n is a traced trip count), which the generic
# Call handling already models. len() is static: it reads the leading
# shape dimension.
_STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr",
                 "type", "str", "repr",
                 "jnp.issubdtype", "jnp.iinfo", "jnp.finfo", "np.iinfo",
                 "np.finfo", "jnp.dtype", "np.dtype"}

# Two taint levels. VAL: a traced array/scalar — branching on it or
# syncing it breaks the compile-once contract. ITEMS: a static-length
# Python CONTAINER holding traced values (tree_flatten output, zip of
# leaf lists) — iterating it is ordinary trace-time Python (the trip
# count is structural), only its ELEMENTS are traced. Telling the two
# apart is what lets the bucketed-collective code (parallel/overlap.py)
# iterate leaf lists without tripping jit/traced-branch.
VAL = "val"
ITEMS = "items"
# structural builders: container-in/container-out, static length
_STRUCTURAL_CALLS = {"zip", "enumerate", "sorted", "reversed", "list",
                     "tuple", "set", "frozenset",
                     "tree_flatten", "tree_leaves",
                     "tree_flatten_with_path", "flatten_up_to",
                     "tree_unflatten", "unflatten",
                     "tree_leaves_with_path"}
# receivers of a method call that sync the device when the value is traced
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
               "jax.device_get", "device_get", "onp.asarray", "onp.array"}
_SYNC_CASTS = {"float", "int", "bool", "complex"}


def _max_level(levels) -> Optional[str]:
    """Strongest taint in a collection: VAL > ITEMS > None."""
    out = None
    for lv in levels:
        if lv is VAL:
            return VAL
        if lv is ITEMS:
            out = ITEMS
    return out


def _val_if(lv) -> Optional[str]:
    """Arithmetic/comparison collapses container-ness to a value."""
    return VAL if lv else None


class _FuncDef:
    def __init__(self, mod: SourceModule, node, cls: Optional[str]):
        self.mod = mod
        self.node = node
        self.cls = cls
        self.name = getattr(node, "name", f"<lambda:{node.lineno}>")
        self.qual = (f"{mod.dotted}.{cls}.{self.name}" if cls
                     else f"{mod.dotted}.{self.name}")


class StepBlockingChecker(Checker):
    """``jit/blocking-in-step``: host syncs and blocking IO lexically
    inside a trainer STEP LOOP.

    The overlap pass (parallel/overlap.py, async checkpointing) exists
    to keep the device ahead of the host; one stray ``float(loss)`` or
    synchronous ``fs.`` write inside the loop that drives the jitted
    step serializes read → transfer → step again and silently undoes
    it. A step loop is recognized lexically: a ``for``/``while`` whose
    body calls ``*.step_fn(...)`` / ``step_fn(...)`` / ``train_step``,
    a callable assigned from ``make_train_step(...)``, or any name
    bound from ``jax.jit(...)`` — the serving engine's device-resident
    step helpers (``_SET_SLOT``/``_SET_TABLE``/``_INJECT``/
    ``self._step_fn``) are jit-bound module or attribute names, and a
    loop dispatching them is exactly as hot as a trainer step loop.
    Inside it (nested defs excluded) the checker flags:

    - ``float()`` / ``int()`` casts of non-literal values, ``.item()``,
      ``.tolist()``, ``.block_until_ready()`` — device round-trips;
    - calls through an ``fs``-named receiver (``self.fs.delete(...)``)
      — synchronous filesystem IO;
    - ``.join()`` with no args / a numeric timeout / a ``timeout=``
      keyword — thread joins (``", ".join(parts)`` stays exempt).

    Annotate deliberate syncs (bounded in-flight backpressure, final
    drain) with ``# lint: disable=jit/blocking-in-step``.
    """

    name = "step-blocking"
    ids = ("jit/blocking-in-step",)

    _SYNC_METHOD_NAMES = {"item", "tolist", "block_until_ready"}

    def check_module(self, mod: SourceModule) -> List[Finding]:
        # names bound from make_train_step(...) or jax.jit(...)
        # anywhere in the module: a loop dispatching a compiled
        # callable IS a step loop, whether it drives training or the
        # serving engine's device-resident state movers
        # kept in two sets so the call FORM must match the binding
        # form: a module-level `_MOVER = jax.jit(...)` marks only bare
        # `_MOVER(...)` calls, a `self._step_fn = jax.jit(...)` only
        # `*. _step_fn(...)` attribute calls — a module that happens to
        # bind jit to a common name (`compile`, `run`) must not turn
        # every `re.compile(...)`-calling loop into a step loop
        step_attrs: Set[str] = {"step_fn", "train_step"}
        step_calls: Set[str] = {"step_fn", "train_step"}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    call_name(node.value):
                cn = call_name(node.value)
                if cn.split(".")[-1] == "make_train_step" or \
                        cn in ("jax.jit", "jit"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            step_calls.add(t.id)
                        elif isinstance(t, ast.Attribute):
                            # self._step_fn = jax.jit(...) — calls
                            # arrive as *.step_fn-style attributes
                            step_attrs.add(t.attr)
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)) and \
                    self._is_step_loop(node, step_attrs, step_calls):
                self._scan_loop(mod, node, findings)
        return findings

    def _is_step_loop(self, loop, step_attrs: Set[str],
                      step_calls: Set[str]) -> bool:
        for node in self._walk_no_defs(loop):
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute) and \
                        fn.attr in step_attrs:
                    return True
                if isinstance(fn, ast.Name) and fn.id in step_calls:
                    return True
        return False

    @staticmethod
    def _walk_no_defs(loop):
        """Walk a loop's body, not descending into nested defs (a
        worker closure defined in the loop runs off the step path)."""
        stack = list(ast.iter_child_nodes(loop))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))

    def _scan_loop(self, mod: SourceModule, loop,
                   findings: List[Finding]) -> None:
        for node in self._walk_no_defs(loop):
            if not isinstance(node, ast.Call):
                continue
            msg = self._blocking_call(node)
            if msg:
                f = mod.finding(
                    node, "jit/blocking-in-step",
                    f"{msg} inside the trainer step loop — it "
                    f"serializes the host against the device step "
                    f"(move it off the loop, make it async, or "
                    f"annotate a deliberate sync)")
                if f:
                    findings.append(f)

    def _blocking_call(self, node: ast.Call) -> Optional[str]:
        name = call_name(node)
        fn = node.func
        if name in ("float", "int") and len(node.args) == 1 and \
                not isinstance(node.args[0], ast.Constant):
            return f"{name}() host-sync cast"
        if isinstance(fn, ast.Attribute):
            if fn.attr in self._SYNC_METHOD_NAMES:
                return f".{fn.attr}() host sync"
            chain = attr_chain(fn)
            if chain and any(seg == "fs" or seg.endswith("_fs")
                             for seg in chain[:-1]):
                return f"blocking filesystem call {'.'.join(chain)}()"
            if fn.attr == "join" and self._looks_like_thread_join(node):
                return "Thread.join()"
        return None

    @staticmethod
    def _looks_like_thread_join(node: ast.Call) -> bool:
        # str.join(iterable) always takes one non-numeric positional;
        # Thread.join takes nothing or a numeric/keyword timeout
        if any(k.arg == "timeout" for k in node.keywords):
            return True
        if not node.args and not node.keywords:
            return True
        return len(node.args) == 1 and \
            isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, (int, float))


class JitDisciplineChecker(Checker):
    name = "jit-discipline"
    ids = ("jit/traced-branch", "jit/host-sync")

    def __init__(self):
        # qual -> _FuncDef for every def in the project
        self._defs: Dict[str, _FuncDef] = {}
        # import maps per module: local name -> qualified target
        self._imports: Dict[str, Dict[str, str]] = {}
        # jit roots: (qual, params statically bound by partial/defaults)
        self._roots: List[Tuple[str, frozenset]] = []
        # defs marked "# lint: static-fn" (trace-time metadata helpers)
        self._static_fns: Set[str] = set()

    # ------------------------------------------------------- collection

    def check_module(self, mod: SourceModule) -> List[Finding]:
        imports: Dict[str, str] = {}
        self._imports[mod.dotted] = imports
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module and \
                    node.level == 0:
                for alias in node.names:
                    imports[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"
        self._index_defs(mod, mod.tree.body, cls=None)
        self._find_roots(mod)
        return []

    def _index_defs(self, mod: SourceModule, body, cls: Optional[str]):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fd = _FuncDef(mod, node, cls)
                self._defs[fd.qual] = fd
                if node.lineno in mod.static_fn_lines:
                    self._static_fns.add(fd.qual)
                # nested defs are reachable via their enclosing scope;
                # index them under the same class for self-resolution
                self._index_defs(mod, node.body, cls)
            elif isinstance(node, ast.ClassDef):
                self._index_defs(mod, node.body, cls=node.name)

    def _find_roots(self, mod: SourceModule) -> None:
        # decorators
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_jit_expr(dec):
                        self._roots.append((self._qual_for(mod, node),
                                            frozenset()))
            if isinstance(node, ast.Call) and self._is_jit_call(node):
                if node.args:
                    target = self._unwrap(mod, node.args[0])
                    if target:
                        self._roots.append(target)

    def _qual_for(self, mod: SourceModule, node) -> str:
        for q, fd in self._defs.items():
            if fd.node is node:
                return q
        return f"{mod.dotted}.{node.name}"

    @staticmethod
    def _is_jit_call(node: ast.Call) -> bool:
        name = call_name(node)
        return name in ("jax.jit", "jit")

    def _is_jit_expr(self, dec: ast.AST) -> bool:
        chain = attr_chain(dec)
        if chain and ".".join(chain) in ("jax.jit", "jit"):
            return True
        if isinstance(dec, ast.Call):
            name = call_name(dec)
            if name in ("jax.jit", "jit"):
                return True
            if name in ("partial", "functools.partial") and dec.args:
                return self._is_jit_expr(dec.args[0])
        return False

    def _unwrap(self, mod: SourceModule, expr: ast.AST, depth: int = 0
                ) -> Optional[Tuple[str, frozenset]]:
        """Resolve the function object inside jax.jit(...): through
        shard_map/partial wrappers, local assignments, lambdas, and
        self-attributes, to (qual, statically-bound-params). Params
        bound by ``partial`` are Python constants at jit-wrap time, so
        they never carry tracers."""
        if depth > 6:
            return None
        if isinstance(expr, ast.Lambda):
            # register the lambda itself as an analysable def: its
            # defaulted params (constant tables) stay static, its
            # call-time params are traced
            fd = _FuncDef(mod, expr, cls=None)
            self._defs.setdefault(fd.qual, fd)
            return (fd.qual, frozenset())
        chain = attr_chain(expr)
        if chain:
            if chain[0] == "self" and len(chain) == 2:
                for q, fd in self._defs.items():
                    if fd.mod is mod and fd.cls and fd.name == chain[1]:
                        return (q, frozenset())
                return None
            dotted = ".".join(chain)
            local = f"{mod.dotted}.{dotted}"
            if local in self._defs:
                return (local, frozenset())
            imported = self._imports.get(mod.dotted, {}).get(dotted)
            if imported in self._defs:
                return (imported, frozenset())
            # a local variable: find its assignment and unwrap the value
            if len(chain) == 1:
                for node in ast.walk(mod.tree):
                    if isinstance(node, ast.Assign):
                        for t in node.targets:
                            if isinstance(t, ast.Name) and \
                                    t.id == chain[0]:
                                got = self._unwrap(mod, node.value,
                                                   depth + 1)
                                if got:
                                    return got
            return None
        if isinstance(expr, ast.Call):
            name = call_name(expr)
            if name in ("shard_map", "_smap", "jax.jit", "jit",
                        "checkpoint", "jax.checkpoint", "remat",
                        "jax.remat", "vmap", "jax.vmap"):
                if expr.args:
                    return self._unwrap(mod, expr.args[0], depth + 1)
            if name in ("partial", "functools.partial") and expr.args:
                got = self._unwrap(mod, expr.args[0], depth + 1)
                if got is None:
                    return None
                qual, static = got
                fd = self._defs.get(qual)
                if fd is None:
                    return got
                params = [a.arg for a in fd.node.args.args
                          if a.arg != "self"]
                bound = set(static)
                # positional partial args bind leading params
                bound.update(params[:len(expr.args) - 1])
                # keyword partial args bind by name
                bound.update(k.arg for k in expr.keywords if k.arg)
                return (qual, frozenset(bound))
        return None

    # -------------------------------------------------------- finalize

    def finalize(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        # worklist of (qual, frozenset of (param, level) pairs)
        seen: Set[Tuple[str, frozenset]] = set()
        work: List[Tuple[str, frozenset]] = []
        for root, static in self._roots:
            fd = self._defs.get(root)
            if fd is None:
                continue
            tainted = frozenset(
                (n, VAL) for n in self._root_tainted_params(fd) - static)
            work.append((root, tainted))
        while work:
            qual, tainted = work.pop()
            if (qual, tainted) in seen:
                continue
            seen.add((qual, tainted))
            fd = self._defs.get(qual)
            if fd is None:
                continue
            calls = self._analyse(fd, dict(tainted), findings)
            for callee, callee_tainted in calls:
                work.append((callee, frozenset(callee_tainted.items())))
        # dedupe (same function may be analysed under several taint sets)
        uniq: Dict[str, Finding] = {}
        for f in findings:
            uniq.setdefault(f.key(), f)
        return list(uniq.values())

    @staticmethod
    def _root_tainted_params(fd: _FuncDef) -> Set[str]:
        """Positional params without defaults are traced; ``self`` and
        defaulted params (constant tables bound at jit time) are not."""
        args = fd.node.args
        n_default = len(args.defaults)
        names = [a.arg for a in args.args]
        cut = len(names) - n_default if n_default else len(names)
        return {n for n in names[:cut] if n != "self"}

    # ---- per-function taint pass

    def _analyse(self, fd: _FuncDef, tainted: Dict[str, str],
                 findings: List[Finding]
                 ) -> List[Tuple[str, Dict[str, str]]]:
        mod = fd.mod
        out_calls: List[Tuple[str, Dict[str, str]]] = []

        def level(e: ast.AST) -> Optional[str]:
            """None (static), VAL (traced value) or ITEMS (static
            container of traced values)."""
            if isinstance(e, ast.Name):
                return tainted.get(e.id)
            if isinstance(e, ast.Attribute):
                if e.attr in _STATIC_ATTRS:
                    return None
                return level(e.value)
            if isinstance(e, ast.Subscript):
                # an element OF a tainted container is a traced value;
                # indexing a static table by static metadata is static,
                # but by a traced index it is a traced gather
                if level(e.value) is not None:
                    return VAL
                if level(e.slice) is VAL:
                    return VAL
                return None
            if isinstance(e, ast.Call):
                name = call_name(e)
                if name in _STATIC_CALLS:
                    return None
                resolved = self._resolve_call(fd, e)
                if resolved is not None and resolved in self._static_fns:
                    return None  # marked "# lint: static-fn"
                last = name.split(".")[-1] if name else ""
                if last in ("astype", "reshape", "sum", "mean", "get"):
                    return level(e.func)
                arg_level = _max_level(
                    [level(a) for a in e.args] +
                    [level(k.value) for k in e.keywords])
                if last in _STRUCTURAL_CALLS:
                    # container-in/container-out, static length:
                    # iterating the result is trace-time Python
                    return ITEMS if arg_level else None
                recv = level(e.func.value) \
                    if isinstance(e.func, ast.Attribute) else None
                return VAL if (arg_level or recv) else None
            if isinstance(e, ast.BinOp):
                return _val_if(level(e.left) or level(e.right))
            if isinstance(e, ast.UnaryOp):
                return level(e.operand)
            if isinstance(e, ast.BoolOp):
                return _max_level([level(v) for v in e.values])
            if isinstance(e, ast.Compare):
                # `x is None` / `x is not None` is trace-time Python
                if all(isinstance(op, (ast.Is, ast.IsNot))
                       for op in e.ops):
                    return None
                # membership over STATIC containers is trace-time too
                if all(isinstance(op, (ast.In, ast.NotIn))
                       for op in e.ops) and \
                        level(e.left) is not VAL and \
                        all(level(c) is not VAL for c in e.comparators):
                    return None
                got = _max_level([level(e.left)] +
                                 [level(c) for c in e.comparators])
                return _val_if(got)
            if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
                return ITEMS if _max_level(
                    [level(el) for el in e.elts]) else None
            if isinstance(e, ast.IfExp):
                return _max_level([level(e.test), level(e.body),
                                   level(e.orelse)])
            if isinstance(e, ast.Starred):
                return level(e.value)
            return None

        def expr_tainted(e: ast.AST) -> bool:
            """A traced VALUE (the thing branches/syncs must not see).
            ITEMS containers are deliberately excluded — their length
            and truthiness are static."""
            return level(e) is VAL

        def assign(target: ast.AST, lv: Optional[str]) -> None:
            if lv is None:
                return
            if isinstance(target, ast.Name):
                tainted[target.id] = lv
            elif isinstance(target, (ast.Tuple, ast.List)):
                # unpacking a metadata tuple keeps container-ness;
                # leaf extraction happens via Subscript/iteration
                for el in target.elts:
                    assign(el, lv)

        def assign_stmt(stmt: ast.Assign) -> None:
            lv = level(stmt.value)
            for t in stmt.targets:
                if isinstance(t, (ast.Tuple, ast.List)) and \
                        isinstance(stmt.value, ast.Tuple) and \
                        len(t.elts) == len(stmt.value.elts):
                    # `a, b = f(x), g(y)` — map levels element-wise
                    for el, val in zip(t.elts, stmt.value.elts):
                        assign(el, level(val))
                else:
                    assign(t, lv)

        def loop_targets(t: ast.AST) -> None:
            # iterating a container (or a traced array) yields traced
            # VALUES in the loop targets
            if isinstance(t, ast.Name):
                tainted[t.id] = VAL
            elif isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    loop_targets(el)

        # two passes so taint flowing backwards through loops settles
        for _ in range(2):
            for stmt in ast.walk(fd.node):
                if isinstance(stmt, ast.Assign):
                    assign_stmt(stmt)
                elif isinstance(stmt, ast.AugAssign) and \
                        (level(stmt.value) or level(stmt.target)):
                    assign(stmt.target, VAL)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)) and \
                        level(stmt.iter) is not None:
                    loop_targets(stmt.target)

        # findings + call propagation
        for node in ast.walk(fd.node):
            if isinstance(node, (ast.If, ast.While)):
                if expr_tainted(node.test):
                    f = mod.finding(
                        node, "jit/traced-branch",
                        f"Python branch on a traced value inside "
                        f"jit-reachable {fd.name}() — every distinct "
                        f"outcome is a retrace (use jnp.where/lax.cond)")
                    if f:
                        findings.append(f)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                # ITEMS iteration is static-trip-count trace Python;
                # only a traced ARRAY as the iterable is a finding
                if level(node.iter) is VAL:
                    f = mod.finding(
                        node, "jit/traced-branch",
                        f"Python loop over a traced value inside "
                        f"jit-reachable {fd.name}() — trip count "
                        f"must be static (use lax.scan/fori_loop)")
                    if f:
                        findings.append(f)
            elif isinstance(node, ast.Call):
                self._check_sync(fd, node, expr_tainted, findings)
                callee = self._resolve_call(fd, node)
                if callee:
                    callee_tainted = self._map_args(callee, node, level)
                    if callee_tainted is not None:
                        out_calls.append((callee, callee_tainted))
        return out_calls

    def _check_sync(self, fd: _FuncDef, node: ast.Call, expr_tainted,
                    findings: List[Finding]) -> None:
        mod = fd.mod
        name = call_name(node)
        msg = None
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SYNC_METHODS and \
                expr_tainted(node.func.value):
            msg = (f".{node.func.attr}() on a traced value inside "
                   f"jit-reachable {fd.name}() forces a host sync")
        elif name in _SYNC_CALLS and any(expr_tainted(a)
                                         for a in node.args):
            msg = (f"{name}() materialises a traced value on the host "
                   f"inside jit-reachable {fd.name}()")
        elif name in _SYNC_CASTS and len(node.args) == 1 and \
                expr_tainted(node.args[0]):
            msg = (f"{name}() on a traced value inside jit-reachable "
                   f"{fd.name}() forces a host sync "
                   f"(use jnp casts / keep it on device)")
        if msg:
            f = mod.finding(node, "jit/host-sync", msg)
            if f:
                findings.append(f)

    def _resolve_call(self, fd: _FuncDef, node: ast.Call) -> Optional[str]:
        chain = attr_chain(node.func)
        if not chain:
            return None
        if chain[0] == "self" and len(chain) == 2 and fd.cls:
            q = f"{fd.mod.dotted}.{fd.cls}.{chain[1]}"
            return q if q in self._defs else None
        dotted = ".".join(chain)
        local = f"{fd.mod.dotted}.{dotted}"
        if local in self._defs:
            return local
        # same-class nested / sibling functions indexed under the class
        if fd.cls and len(chain) == 1:
            q = f"{fd.mod.dotted}.{fd.cls}.{chain[0]}"
            if q in self._defs:
                return q
        imported = self._imports.get(fd.mod.dotted, {}).get(dotted)
        if imported in self._defs:
            return imported
        return None

    def _map_args(self, callee_qual: str, call: ast.Call,
                  level) -> Optional[Dict[str, str]]:
        """Map argument taint LEVELS onto callee params (positional and
        keyword) so an ITEMS container stays iterable in the callee;
        returns None when nothing tainted flows in."""
        callee = self._defs[callee_qual]
        params = [a.arg for a in callee.node.args.args]
        if params and params[0] == "self":
            params = params[1:]
        tainted: Dict[str, str] = {}
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                lv = level(arg.value)
                if lv:
                    for p in params[i:]:
                        tainted[p] = VAL
                break
            lv = level(arg)
            if i < len(params) and lv:
                tainted[params[i]] = lv
        for kw in call.keywords:
            lv = level(kw.value)
            if kw.arg and lv and \
                    kw.arg in [a.arg for a in callee.node.args.args]:
                tainted[kw.arg] = lv
        return tainted if tainted else None
