"""Lock discipline: guarded-by enforcement + lock-order cycle detection.

The reference encodes these as ``@GuardedBy`` annotations checked by
findbugs and a documented FSNamesystem → BlockManager lock order; here the
annotation is a line comment on the field's initialising assignment::

    self._free = deque(...)   # guarded-by: _lock

and every other ``self._free`` access in the class must sit inside a
``with self._lock`` (or ``with self._lock.read()/.write()`` — the
namesystem RW lock) scope. Helper methods documented as called under the
lock mark themselves ``# lint: holds=_lock`` on their ``def`` line.

The order checker builds one graph for the whole run: node =
``Class.lockattr`` (or ``module.lockvar``), edge A→B when B is acquired
— lexically, or via a resolvable same-class/same-module call — while A is
held. Any strongly-connected component is a schedulable deadlock.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from hadoop_tpu.analysis.core import (Checker, Finding, Project,
                                      SourceModule, attr_chain)

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition", "NamesystemLock"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    chain = attr_chain(node.func)
    return bool(chain) and ".".join(chain) in _LOCK_CTORS


def _with_lock_names(stmt: ast.With) -> List[str]:
    """Lock attribute names acquired by a ``with`` statement: matches
    ``self.X``, ``self.X.read()/.write()/...()``, and bare module-level
    ``X`` / ``X.acquire_shared()`` style items."""
    out = []
    for item in stmt.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):     # self.lock.write() / lock.held()
            expr = expr.func
            if isinstance(expr, ast.Attribute):
                expr = expr.value          # drop the method
        chain = attr_chain(expr)
        if not chain:
            continue
        if chain[0] == "self" and len(chain) >= 2:
            out.append(chain[1])
        elif len(chain) == 1:
            out.append(chain[0])
    return out


class _ClassInfo:
    def __init__(self, module: SourceModule, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.lock_attrs: Set[str] = set()
        self.guards: Dict[str, Tuple[str, int]] = {}  # field -> (lock, line)
        # find lock fields and guarded fields from __init__-level
        # assignments anywhere in the class body
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                value = sub.value
                for t in targets:
                    chain = attr_chain(t)
                    if not chain or chain[0] != "self" or len(chain) != 2:
                        continue
                    field = chain[1]
                    if value is not None and _is_lock_ctor(value):
                        self.lock_attrs.add(field)
                    guard = module.guards.get(sub.lineno)
                    if guard:
                        self.guards[field] = (guard, sub.lineno)


class GuardedByChecker(Checker):
    """``lock/guarded-by`` — a field annotated ``# guarded-by: <lock>``
    touched outside a ``with self.<lock>`` scope."""

    name = "guarded-by"
    ids = ("lock/guarded-by",)

    # methods where unguarded access is inherent: construction (object
    # not yet shared) and destruction (object no longer shared)
    _EXEMPT = {"__init__", "__del__", "__repr__", "__str__"}

    def check_module(self, mod: SourceModule) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                info = _ClassInfo(mod, node)
                if info.guards:
                    findings.extend(self._check_class(mod, info))
        return [f for f in findings if f is not None]

    def _check_class(self, mod: SourceModule,
                     info: _ClassInfo) -> List[Finding]:
        findings: List[Finding] = []
        for item in info.node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name in self._EXEMPT:
                continue
            held0 = set(mod.holds.get(item.lineno, ()))
            self._walk(mod, info, item.body, held0, item, findings)
        return findings

    def _walk(self, mod: SourceModule, info: _ClassInfo,
              stmts: Sequence[ast.stmt], held: Set[str],
              func: ast.AST, findings: List[Finding]) -> None:
        for stmt in stmts:
            for expr_field in self._accesses_in(stmt):
                self._report(mod, info, expr_field, held, findings)
            if isinstance(stmt, ast.With):
                inner = held | set(_with_lock_names(stmt))
                self._walk(mod, info, stmt.body, inner, func, findings)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: a closure runs later, possibly unlocked —
                # unless its def line carries its own holds annotation
                inner_held = set(mod.holds.get(stmt.lineno, ()))
                self._walk(mod, info, stmt.body, inner_held, stmt, findings)
            elif isinstance(stmt, (ast.If, ast.While, ast.For,
                                   ast.AsyncFor)):
                self._walk(mod, info, stmt.body, held, func, findings)
                self._walk(mod, info, stmt.orelse, held, func, findings)
            elif isinstance(stmt, ast.Try):
                self._walk(mod, info, stmt.body, held, func, findings)
                for h in stmt.handlers:
                    self._walk(mod, info, h.body, held, func, findings)
                self._walk(mod, info, stmt.orelse, held, func, findings)
                self._walk(mod, info, stmt.finalbody, held, func, findings)

    def _accesses_in(self, stmt: ast.stmt) -> List[Tuple[ast.AST, str]]:
        """(node, field) for every self.<field> touch in expression
        position of the statement HEADER only — nested bodies are walked
        separately with their own held sets."""
        out: List[Tuple[ast.AST, str]] = []
        for n in self._shallow(stmt):
            for sub in ast.walk(n):
                if isinstance(sub, ast.Attribute) and \
                        isinstance(sub.value, ast.Name) and \
                        sub.value.id == "self":
                    out.append((sub, sub.attr))
        return out

    @staticmethod
    def _shallow(stmt: ast.stmt) -> List[ast.AST]:
        """Header expressions of a statement (bodies excluded)."""
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.target, stmt.iter]
        if isinstance(stmt, ast.With):
            return [i.context_expr for i in stmt.items]
        if isinstance(stmt, (ast.Try, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            return []
        return [stmt]

    def _report(self, mod: SourceModule, info: _ClassInfo,
                expr_field: Tuple[ast.AST, str], held: Set[str],
                findings: List[Finding]) -> None:
        node, field = expr_field
        spec = info.guards.get(field)
        if spec is None:
            return
        lock, _ = spec
        lock_head = lock.split(".")[0]
        if lock_head in held:
            return
        f = mod.finding(node, "lock/guarded-by",
                        f"{info.name}.{field} is guarded by "
                        f"self.{lock} but accessed without it")
        if f is not None:
            findings.append(f)


# ------------------------------------------------------------- lock order

class _FuncFacts:
    """Per-function lock facts for the order graph."""

    def __init__(self, qual: str):
        self.qual = qual                       # Module.Class.method
        self.acquires: Set[str] = set()        # lock nodes taken anywhere
        # (held_lock, callee_qual) — call made while holding held_lock
        self.calls_under: List[Tuple[str, str, str, int]] = []
        # (outer, inner, rel, line) direct lexical nesting edges
        self.nest_edges: List[Tuple[str, str, str, int]] = []


class LockOrderChecker(Checker):
    """``lock/order-cycle`` — the project-wide lock acquisition graph
    contains a cycle (two threads can deadlock by taking the locks in
    opposite orders)."""

    name = "lock-order"
    ids = ("lock/order-cycle",)

    def __init__(self):
        self._funcs: Dict[str, _FuncFacts] = {}
        self._suppress_lines: Dict[str, SourceModule] = {}

    # ---- per-module collection

    def check_module(self, mod: SourceModule) -> List[Finding]:
        module_locks = self._module_level_locks(mod)
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                info = _ClassInfo(mod, node)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._collect(mod, item,
                                      cls=info, module_locks=module_locks)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect(mod, node, cls=None,
                              module_locks=module_locks)
        return []

    @staticmethod
    def _module_level_locks(mod: SourceModule) -> Set[str]:
        out: Set[str] = set()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    def _lock_node(self, mod: SourceModule, cls: Optional[_ClassInfo],
                   module_locks: Set[str], name: str) -> Optional[str]:
        """Map a with-acquired attribute/name to a graph node, only for
        objects we KNOW are locks (declared in this class/module)."""
        if cls is not None and name in cls.lock_attrs:
            return f"{cls.name}.{name}"
        if name in module_locks:
            return f"{mod.dotted}.{name}"
        # the namesystem RW lock: self.lock = NamesystemLock(...)
        return None

    def _collect(self, mod: SourceModule, func: ast.AST,
                 cls: Optional[_ClassInfo],
                 module_locks: Set[str]) -> None:
        qual = f"{mod.dotted}.{cls.name}.{func.name}" if cls else \
            f"{mod.dotted}.{func.name}"
        facts = _FuncFacts(qual)
        self._funcs[qual] = facts
        self._suppress_lines[qual] = mod

        def walk(stmts, held: List[Tuple[str, int]]):
            for stmt in stmts:
                if isinstance(stmt, ast.With):
                    taken = []
                    for name in _with_lock_names(stmt):
                        ln = self._lock_node(mod, cls, module_locks, name)
                        if ln is not None:
                            facts.acquires.add(ln)
                            if held:
                                outer = held[-1][0]
                                facts.nest_edges.append(
                                    (outer, ln, mod.rel, stmt.lineno))
                            taken.append((ln, stmt.lineno))
                    walk(stmt.body, held + taken)
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    walk(stmt.body, [])   # closure: lock state unknown
                else:
                    if held:
                        for call in ast.walk(stmt):
                            if isinstance(call, ast.Call):
                                callee = self._resolve(mod, cls, call)
                                if callee:
                                    facts.calls_under.append(
                                        (held[-1][0], callee, mod.rel,
                                         call.lineno))
                    if isinstance(stmt, (ast.If, ast.While, ast.For,
                                         ast.AsyncFor)):
                        walk(stmt.body, held)
                        walk(stmt.orelse, held)
                    elif isinstance(stmt, ast.Try):
                        walk(stmt.body, held)
                        for h in stmt.handlers:
                            walk(h.body, held)
                        walk(stmt.orelse, held)
                        walk(stmt.finalbody, held)

        walk(func.body, [])

    def _resolve(self, mod: SourceModule, cls: Optional[_ClassInfo],
                 call: ast.Call) -> Optional[str]:
        chain = attr_chain(call.func)
        if not chain:
            return None
        if chain[0] == "self" and len(chain) == 2 and cls is not None:
            return f"{mod.dotted}.{cls.name}.{chain[1]}"
        if len(chain) == 1:
            return f"{mod.dotted}.{chain[0]}"
        return None

    # ---- whole-project graph

    def finalize(self, project: Project) -> List[Finding]:
        # transitive acquires through resolvable calls (fixpoint)
        acquires: Dict[str, Set[str]] = {
            q: set(f.acquires) for q, f in self._funcs.items()}
        callees: Dict[str, Set[str]] = {}
        for q, f in self._funcs.items():
            callees[q] = {c for _, c, _, _ in f.calls_under}
        changed = True
        rounds = 0
        while changed and rounds < 20:
            changed = False
            rounds += 1
            for q, f in self._funcs.items():
                for c in callees[q]:
                    extra = acquires.get(c)
                    if extra and not extra <= acquires[q]:
                        acquires[q] |= extra
                        changed = True
        # edges: lexical nesting + "call under lock reaches an acquire"
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for q, f in self._funcs.items():
            for outer, inner, rel, line in f.nest_edges:
                if outer != inner:
                    edges.setdefault((outer, inner), (rel, line))
            for held, callee, rel, line in f.calls_under:
                for inner in acquires.get(callee, ()):
                    if inner != held:
                        edges.setdefault((held, inner), (rel, line))
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        findings: List[Finding] = []
        for cycle in self._cycles(graph):
            # anchor the finding at some edge inside the cycle
            members = set(cycle)
            rel, line = next((loc for (a, b), loc in sorted(edges.items())
                              if a in members and b in members),
                             ("<unknown>", 1))
            path = " -> ".join(cycle + [cycle[0]])
            mod = next((m for m in project.modules if m.rel == rel), None)
            if mod is not None and mod.is_suppressed(line,
                                                     "lock/order-cycle"):
                continue
            findings.append(Finding(
                rel, line, "lock/order-cycle",
                f"lock acquisition order cycle: {path} — two threads "
                f"taking these locks in opposite orders deadlock"))
        return findings

    @staticmethod
    def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
        """Strongly connected components of size > 1 (or a self-loop),
        via iterative Tarjan; each SCC is reported once, deterministically
        ordered."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        for root in sorted(graph):
            if root in index:
                continue
            work = [(root, iter(sorted(graph.get(root, ()))))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                        advanced = True
                        break
                    elif nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1 or node in graph.get(node, ()):
                        sccs.append(sorted(comp))
        return sccs
