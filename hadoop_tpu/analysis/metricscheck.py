"""Metrics exposition discipline — the ``/prom`` plane's two footguns.

``metrics/duplicate-family`` — the same Prometheus family name
registered with two different metric kinds anywhere in the project.
``/prom`` merges same-named families across every source (per-port
xceiver registries, per-server rpc registries) into one TYPE'd group;
a ``counter`` named ``x`` in one module and a ``gauge`` named ``x`` in
another silently drops whichever registers second (prom.py skips
type-conflicting families), so the dashboard reading ``htpu_x`` sees
half the fleet. Caught statically at the second registration site.

``metrics/unbounded-label`` — a ``prom_labels`` value that is not
provably drawn from a bounded literal set. Prometheus label values
create one series each; a label derived from request or user data
(a path, a tenant name, an f-string with a port in it) is a cardinality
bomb that OOMs the scraper a week later. Allowed: constants, and names
bound by a ``for``/comprehension iterating a literal tuple/list/set of
constants (the ``{"tier": tier} for tier in ("host", "dfs")`` idiom).
Everything else — parameters, attributes, calls, f-strings — flags.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from hadoop_tpu.analysis.core import (Checker, Finding, Project,
                                      SourceModule)

# metric-factory method name -> the (prom kind, family name) pairs it
# mints (mirrors metrics/prom.py's rendering exactly — a rate becomes
# one counter family and one gauge family)
_FACTORIES = {
    "counter": (("counter", "{n}_total"),),
    "gauge": (("gauge", "{n}"),),
    "register_callback_gauge": (("gauge", "{n}"),),
    "rate": (("counter", "{n}_num_ops_total"), ("gauge", "{n}_avg_time")),
    "quantiles": (("summary", "{n}"),),
    "histogram": (("histogram", "{n}"),),
}


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _literal_iterable_names(func: ast.AST) -> Set[str]:
    """Names bound (anywhere in ``func``) by a for-loop or comprehension
    whose iterable is a literal container of constants — bounded by
    construction."""
    bounded: Set[str] = set()

    def literal(it: ast.AST) -> bool:
        return isinstance(it, (ast.Tuple, ast.List, ast.Set)) and \
            all(isinstance(e, ast.Constant) for e in it.elts)

    def bind(target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            bounded.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                bind(e)

    for node in ast.walk(func):
        if isinstance(node, ast.For) and literal(node.iter):
            bind(node.target)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                if literal(gen.iter):
                    bind(gen.target)
    return bounded


class PromFamilyChecker(Checker):
    name = "metrics-prom"
    ids = ("metrics/duplicate-family", "metrics/unbounded-label")

    def __init__(self):
        # family -> (kind, module rel path, line) of first registration
        self._families: Dict[str, Tuple[str, str, int]] = {}
        self._findings: List[Finding] = []

    def check_module(self, mod: SourceModule) -> List[Finding]:
        findings: List[Finding] = []
        # enclosing-function context for bounded-name resolution
        funcs = [n for n in ast.walk(mod.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Module))]
        bounded_by_func = {id(f): _literal_iterable_names(f)
                           for f in funcs}
        # map every call to its nearest enclosing function
        parents: Dict[int, ast.AST] = {}
        for f in funcs:
            for node in ast.walk(f):
                if isinstance(node, ast.Call):
                    # nearest wins: later (inner) functions overwrite
                    parents[id(node)] = f
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            factory = _FACTORIES.get(node.func.attr)
            if factory is None:
                continue
            raw_name = _const_str(node.args[0]) if node.args else None
            prom_name = None
            labels_node = None
            for kw in node.keywords:
                if kw.arg == "prom_name":
                    prom_name = _const_str(kw.value)
                elif kw.arg == "prom_labels":
                    labels_node = kw.value
            # counters/gauges/histograms/callback gauges all honor the
            # prom_name exposition override (metrics/prom.py): the
            # FAMILY a scraper sees is prom_name, so that is what the
            # duplicate-family ledger must key on
            base = prom_name if (node.func.attr in (
                "histogram", "counter", "gauge",
                "register_callback_gauge") and prom_name is not None) \
                else raw_name
            if base is not None:
                for kind, form in factory:
                    self._note_family(mod, node, kind,
                                      form.format(n=base))
            if labels_node is not None:
                bounded = bounded_by_func.get(
                    id(parents.get(id(node))), set())
                self._check_labels(mod, node, labels_node, bounded,
                                   findings)
        return findings

    # ------------------------------------------------------------ families

    def _note_family(self, mod: SourceModule, node: ast.Call, kind: str,
                     family: str) -> None:
        prior = self._families.get(family)
        if prior is None:
            self._families[family] = (kind, mod.rel, node.lineno)
            return
        p_kind, p_mod, p_line = prior
        if p_kind != kind:
            f = mod.finding(
                node, "metrics/duplicate-family",
                f"/prom family '{family}' registered as {kind} here but "
                f"as {p_kind} at {p_mod}:{p_line} — same-named families "
                f"merge across sources and conflicting types are "
                f"silently dropped")
            if f is not None:
                self._findings.append(f)

    def finalize(self, project: Project) -> List[Finding]:
        out = self._findings
        self._findings = []
        return out

    # -------------------------------------------------------------- labels

    def _check_labels(self, mod: SourceModule, call: ast.Call,
                      labels: ast.AST, bounded: Set[str],
                      findings: List[Finding]) -> None:
        if not isinstance(labels, ast.Dict):
            f = mod.finding(call, "metrics/unbounded-label",
                            "prom_labels built dynamically — label "
                            "values must come from a bounded literal "
                            "set (one Prometheus series per value)")
            if f is not None:
                findings.append(f)
            return
        for v in labels.values:
            if v is None:
                continue                       # dict-unpacking: opaque
            if isinstance(v, ast.Constant):
                continue
            if isinstance(v, ast.Name) and v.id in bounded:
                continue                       # for x in ("a", "b")
            f = mod.finding(
                v, "metrics/unbounded-label",
                "prom label value is not drawn from a bounded literal "
                "set — a label derived from request/user data mints "
                "one series per distinct value (cardinality bomb)")
            if f is not None:
                findings.append(f)
