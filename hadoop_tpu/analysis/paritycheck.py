"""Parity-tier discipline — the relaxed plane stays behind its gate.

``parity/relaxed-gated`` — a call to a quantized-collective or
chunked-matmul entry point (the relaxed parity tier,
``hadoop_tpu/parallel/lowp``) that is not lexically inside a guard
naming the relaxed tier. The tier's whole contract is that
``parallel.parity=bitwise`` (the default) compiles byte-identical
graphs with zero lowp code reachable; one unguarded call site quietly
quantizes a collective for every user and turns the bitwise parity
tests into liars. The guard is judged lexically: some enclosing ``if``
(or ternary) whose test mentions an identifier containing ``relaxed``
— ``if ctx.relaxed_codec is not None:``, ``if relaxed is not None:``,
``if parity.relaxed:`` all qualify — which is also why the tier's
plumbing NAMES everything ``relaxed``. Definitions inside the lowp
package itself are exempt (they are the tier).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from hadoop_tpu.analysis.core import (Checker, Finding, SourceModule,
                                      attr_chain)

# the relaxed tier's entry points: the in-graph quantized collectives
# (parallel/lowp/quant.py) and the reassociating chunked matmul
# (ops/collective_matmul.py). Matched by trailing name so both
# `psum_quantized(...)` and `quant.psum_quantized(...)` resolve.
ENTRY_POINTS = frozenset({
    "psum_quantized",
    "psum_scatter_quantized",
    "psum_of_scatter_quantized",
    "chunked_matmul_reduce",
})

_LOWP_PKG = "hadoop_tpu.parallel.lowp"


def _mentions_relaxed(test: ast.AST) -> bool:
    """Does the guard expression name the relaxed tier? Any identifier
    (Name, attribute, keyword-arg name, string constant) containing
    "relaxed" counts."""
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and "relaxed" in node.id.lower():
            return True
        if isinstance(node, ast.Attribute) and \
                "relaxed" in node.attr.lower():
            return True
        if isinstance(node, ast.keyword) and node.arg and \
                "relaxed" in node.arg.lower():
            return True
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                "relaxed" in node.value.lower():
            return True
    return False


class RelaxedGateChecker(Checker):
    name = "parity"
    ids = ("parity/relaxed-gated",)

    def check_module(self, mod: SourceModule) -> List[Finding]:
        if mod.dotted == _LOWP_PKG or \
                mod.dotted.startswith(_LOWP_PKG + "."):
            return []   # the tier itself
        findings: List[Finding] = []
        # entry points stay entry points under a rename
        # (`from ...lowp.quant import psum_quantized as pq`); other
        # lowp symbols (ParityConfig, the guard harness, the host
        # payload codec) are tier PLUMBING, not quantized paths
        imported: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module and \
                    node.module.startswith(_LOWP_PKG):
                for alias in node.names:
                    if alias.name in ENTRY_POINTS:
                        imported.add(alias.asname or alias.name)
        self._walk(mod, mod.tree, imported, guarded=False,
                   findings=findings)
        return findings

    # --------------------------------------------------------------- walk

    def _walk(self, mod: SourceModule, node: ast.AST, imported: Set[str],
              guarded: bool, findings: List[Finding]) -> None:
        """Recursive descent carrying whether a relaxed-naming guard
        encloses the current position. Only `if`/ternary tests open a
        guard; everything else propagates the flag."""
        for child in ast.iter_child_nodes(node):
            child_guarded = guarded
            if isinstance(child, ast.If) and \
                    _mentions_relaxed(child.test):
                # both arms: the else of `if not ...relaxed...: return`
                # style early-outs is still tier-aware code; flagging
                # the else arm would force contortions for no safety
                child_guarded = True
            if isinstance(child, ast.IfExp) and \
                    _mentions_relaxed(child.test):
                child_guarded = True
            if isinstance(child, ast.Call):
                name = self._entry_name(child, imported)
                if name is not None and not child_guarded:
                    f = mod.finding(
                        child, "parity/relaxed-gated",
                        f"relaxed-tier entry point {name}() reached "
                        f"without a relaxed-parity guard — quantized "
                        f"collectives / chunked matmul must be "
                        f"unreachable under parallel.parity=bitwise "
                        f"(enclose in an `if ...relaxed...:` branch)")
                    if f is not None:
                        findings.append(f)
            self._walk(mod, child, imported, child_guarded, findings)

    def _entry_name(self, call: ast.Call,
                    imported: Set[str]) -> Optional[str]:
        chain = attr_chain(call.func)
        if not chain:
            return None
        tail = chain[-1]
        if tail in ENTRY_POINTS:
            return tail
        if len(chain) == 1 and chain[0] in imported:
            return chain[0]   # renamed entry-point import
        return None
