"""Parity-tier discipline — the relaxed plane stays behind its gate.

``parity/relaxed-gated`` — a call to a quantized-collective,
chunked-matmul, quantized-weight or context-parallel-serving entry
point (the relaxed parity tiers: ``parallel.parity`` for the training
communication plane in ``hadoop_tpu/parallel/lowp``, ``serving.parity``
for the serving weight plane in ``hadoop_tpu/serving/weightplane.py``
and the long-context plane in ``hadoop_tpu/serving/longctx/``) that is
not lexically inside a guard naming the relaxed tier. Each tier's whole
contract is that its bitwise default compiles byte-identical graphs
with zero quantized code reachable; one unguarded call site quietly
quantizes a collective (or a resident weight) for every user and
turns the bitwise parity tests into liars. The guard is judged
lexically: some enclosing ``if`` (or ternary) whose test mentions an
identifier containing ``relaxed`` — ``if ctx.relaxed_codec is not
None:``, ``if relaxed is not None:``, ``if self._relaxed_weights:``
all qualify — which is also why both tiers' plumbing NAMES everything
``relaxed``. Definitions inside the tier packages themselves are
exempt (they are the tier).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from hadoop_tpu.analysis.core import (Checker, Finding, SourceModule,
                                      attr_chain)

# the relaxed tiers' entry points: the in-graph quantized collectives
# (parallel/lowp/quant.py), the reassociating chunked matmul
# (ops/collective_matmul.py), and the serving weight plane's
# dequantizing matmul/gather/head + its quantize-at-load seam
# (serving/weightplane.py). Matched by trailing name so both
# `psum_quantized(...)` and `quant.psum_quantized(...)` resolve.
ENTRY_POINTS = frozenset({
    "psum_quantized",
    "psum_scatter_quantized",
    "psum_of_scatter_quantized",
    "chunked_matmul_reduce",
    # partially-synchronized sync schedules (parallel/lowp/syncpolicy):
    # a scheduled-off layer's reduce replacement — skipping or staling
    # a TP activation sync outside a relaxed guard would silently make
    # the bitwise tier's activations rank-divergent
    "scheduled_row_reduce",
    "skip_row_reduce",
    "stale_row_reduce",
    # serving weight plane (serving.parity)
    "qdot",
    "qrows",
    "qhead",
    "qslice",
    "qedot",
    "quantized_load",
    # MoE expert-parallel serving: the int8 all2all payload codecs
    # (parallel/lowp/quant.py) — an unguarded leg would quantize every
    # bitwise MoE replica's dispatch/combine exchange
    "moe_dispatch_quantized",
    "moe_combine_quantized",
    # long-context serving plane (serving.parity): CP prefill
    # reassociates the softmax across ranks, paged decode across
    # windows — neither is bitwise vs the single-chip step
    "cp_prefill",
    "paged_decode",
    "longctx_submit",
    "longctx_plane_from_conf",
})

_LOWP_PKG = "hadoop_tpu.parallel.lowp"
_WEIGHTPLANE_MOD = "hadoop_tpu.serving.weightplane"
_LONGCTX_PKG = "hadoop_tpu.serving.longctx"


def _mentions_relaxed(test: ast.AST) -> bool:
    """Does the guard expression name the relaxed tier? Any identifier
    (Name, attribute, keyword-arg name, string constant) containing
    "relaxed" counts."""
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and "relaxed" in node.id.lower():
            return True
        if isinstance(node, ast.Attribute) and \
                "relaxed" in node.attr.lower():
            return True
        if isinstance(node, ast.keyword) and node.arg and \
                "relaxed" in node.arg.lower():
            return True
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                "relaxed" in node.value.lower():
            return True
    return False


class RelaxedGateChecker(Checker):
    name = "parity"
    ids = ("parity/relaxed-gated",)

    def check_module(self, mod: SourceModule) -> List[Finding]:
        if mod.dotted == _LOWP_PKG or \
                mod.dotted.startswith(_LOWP_PKG + ".") or \
                mod.dotted == _WEIGHTPLANE_MOD or \
                mod.dotted == _LONGCTX_PKG or \
                mod.dotted.startswith(_LONGCTX_PKG + "."):
            return []   # the tiers themselves
        findings: List[Finding] = []
        # entry points stay entry points under a rename
        # (`from ...lowp.quant import psum_quantized as pq`); other
        # tier symbols (ParityConfig/WeightPlaneConfig, the guard
        # harnesses, the host payload codecs) are tier PLUMBING, not
        # quantized paths
        imported: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module and \
                    (node.module.startswith(_LOWP_PKG) or
                     node.module == _WEIGHTPLANE_MOD or
                     node.module.startswith(_LONGCTX_PKG)):
                for alias in node.names:
                    if alias.name in ENTRY_POINTS:
                        imported.add(alias.asname or alias.name)
        self._walk(mod, mod.tree, imported, guarded=False,
                   findings=findings)
        return findings

    # --------------------------------------------------------------- walk

    def _walk(self, mod: SourceModule, node: ast.AST, imported: Set[str],
              guarded: bool, findings: List[Finding]) -> None:
        """Recursive descent carrying whether a relaxed-naming guard
        encloses the current position. Only `if`/ternary tests open a
        guard; everything else propagates the flag."""
        for child in ast.iter_child_nodes(node):
            child_guarded = guarded
            if isinstance(child, ast.If) and \
                    _mentions_relaxed(child.test):
                # both arms: the else of `if not ...relaxed...: return`
                # style early-outs is still tier-aware code; flagging
                # the else arm would force contortions for no safety
                child_guarded = True
            if isinstance(child, ast.IfExp) and \
                    _mentions_relaxed(child.test):
                child_guarded = True
            if isinstance(child, ast.Call):
                name = self._entry_name(child, imported)
                if name is not None and not child_guarded:
                    f = mod.finding(
                        child, "parity/relaxed-gated",
                        f"relaxed-tier entry point {name}() reached "
                        f"without a relaxed-parity guard — quantized "
                        f"collectives / chunked matmul must be "
                        f"unreachable under parallel.parity=bitwise "
                        f"(enclose in an `if ...relaxed...:` branch)")
                    if f is not None:
                        findings.append(f)
            self._walk(mod, child, imported, child_guarded, findings)

    def _entry_name(self, call: ast.Call,
                    imported: Set[str]) -> Optional[str]:
        chain = attr_chain(call.func)
        if not chain:
            return None
        tail = chain[-1]
        if tail in ENTRY_POINTS:
            return tail
        if len(chain) == 1 and chain[0] in imported:
            return chain[0]   # renamed entry-point import
        return None
