"""RPC / retry hygiene checkers.

Every network call in a fleet must be bounded by a timeout (a hung peer
otherwise blocks the caller forever), retries must back off with jitter
(constant-sleep retry loops synchronise a fleet into retry storms —
ref: io/retry/RetryPolicies exponential policies), and failures must
leave a breadcrumb (silent broad ``except: pass`` swallows the evidence).

``rpc/no-timeout``        ``socket.create_connection``/``urlopen``/
                          ``HTTPConnection`` without a timeout, or a
                          ``socket.socket()`` connected without a prior
                          ``settimeout`` in the same function.
``rpc/timeout-cleared``   ``x.settimeout(None)`` — unbounds every later
                          recv/send on a live connection.
``rpc/retry-no-backoff``  ``time.sleep(<constant>)`` inside a loop that
                          catches exceptions: the retry cadence neither
                          grows nor jitters.
``rpc/silent-swallow``    ``except:`` / ``except Exception:`` with a
                          body of ``pass``.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from hadoop_tpu.analysis.core import (Checker, Finding, SourceModule,
                                      attr_chain, call_name)

_CONNECT_CALLS = {"socket.create_connection", "create_connection"}
_HTTP_CTORS = {"HTTPConnection", "HTTPSConnection",
               "http.client.HTTPConnection", "http.client.HTTPSConnection",
               "httplib.HTTPConnection"}
_URLOPEN = {"urlopen", "urllib.request.urlopen", "request.urlopen"}


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(k.arg == name for k in call.keywords)


class TimeoutChecker(Checker):
    name = "rpc-timeout"
    ids = ("rpc/no-timeout", "rpc/timeout-cleared")

    def check_module(self, mod: SourceModule) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(mod, node, findings)
        # module-level code too (scripts)
        self._check_calls(mod, mod.tree.body, set(), set(), findings,
                          toplevel=True)
        return findings

    def _check_function(self, mod: SourceModule, func, findings) -> None:
        raw_socks: Set[str] = set()
        timed: Set[str] = set()
        # pass 1: names bound to socket.socket() and names .settimeout()ed
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                name = call_name(node.value)
                if name in ("socket.socket", "socket"):
                    for t in node.targets:
                        chain = attr_chain(t)
                        if chain:
                            raw_socks.add(".".join(chain))
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("settimeout", "setblocking"):
                chain = attr_chain(node.func.value)
                if chain:
                    timed.add(".".join(chain))
        self._check_calls(mod, [func], raw_socks, timed, findings)

    def _check_calls(self, mod: SourceModule, roots, raw_socks: Set[str],
                     timed: Set[str], findings: List[Finding],
                     toplevel: bool = False) -> None:
        for root in roots:
            if toplevel and isinstance(root, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.ClassDef)):
                continue  # functions/methods get their own pass
            for node in ast.walk(root):
                if isinstance(node, ast.Call):
                    self._check_one(mod, node, raw_socks, timed, findings)

    def _check_one(self, mod: SourceModule, node: ast.Call,
                   raw_socks: Set[str], timed: Set[str],
                   findings: List[Finding]) -> None:
        name = call_name(node)
        f: Optional[Finding] = None
        if name in _CONNECT_CALLS:
            if len(node.args) < 2 and not _has_kw(node, "timeout"):
                f = mod.finding(node, "rpc/no-timeout",
                                "create_connection without a timeout — a "
                                "black-holed peer blocks the caller "
                                "forever")
        elif name and name.split(".")[-1] in ("HTTPConnection",
                                              "HTTPSConnection") and \
                (name in _HTTP_CTORS or name.split(".")[-1] == name):
            if not _has_kw(node, "timeout"):
                f = mod.finding(node, "rpc/no-timeout",
                                f"{name.split('.')[-1]} without a timeout")
        elif name in _URLOPEN:
            if len(node.args) < 3 and not _has_kw(node, "timeout"):
                f = mod.finding(node, "rpc/no-timeout",
                                "urlopen without a timeout")
        elif isinstance(node.func, ast.Attribute):
            if node.func.attr == "settimeout" and len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value is None:
                f = mod.finding(node, "rpc/timeout-cleared",
                                "settimeout(None) unbounds every later "
                                "recv/send on this connection — use a "
                                "configurable read timeout")
            elif node.func.attr == "connect":
                chain = attr_chain(node.func.value)
                dotted = ".".join(chain) if chain else None
                if dotted and dotted in raw_socks and dotted not in timed:
                    f = mod.finding(node, "rpc/no-timeout",
                                    f"{dotted}.connect() on a socket with "
                                    f"no settimeout in this function")
        if f is not None:
            findings.append(f)


class RetryHygieneChecker(Checker):
    name = "retry-hygiene"
    ids = ("rpc/retry-no-backoff",)

    def check_module(self, mod: SourceModule) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.For, ast.While)):
                self._check_loop(mod, node, findings)
        return findings

    def _check_loop(self, mod: SourceModule, loop, findings) -> None:
        # retry shape: the loop body catches exceptions somewhere
        has_try = any(isinstance(n, ast.Try) for n in ast.walk(loop))
        if not has_try:
            return
        # names whose value varies per iteration: loop targets + anything
        # (re)assigned inside the loop body
        varying: Set[str] = set()
        if isinstance(loop, ast.For):
            varying.update(self._names(loop.target))
        for n in ast.walk(loop):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    varying.update(self._names(t))
            elif isinstance(n, ast.AugAssign):
                varying.update(self._names(n.target))
        for n in ast.walk(loop):
            if not (isinstance(n, ast.Call) and
                    call_name(n) in ("time.sleep", "sleep", "_time.sleep",
                                     "_t.sleep")):
                continue
            if not n.args:
                continue
            arg = n.args[0]
            if self._is_constant_delay(arg, varying):
                f = mod.finding(
                    n, "rpc/retry-no-backoff",
                    "retry loop sleeps a constant delay — add "
                    "exponential backoff + jitter (util.misc."
                    "backoff_delay) or the fleet retries in lockstep")
                if f:
                    findings.append(f)

    @staticmethod
    def _names(t: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                out.add(sub.id)
        return out

    @staticmethod
    def _is_constant_delay(arg: ast.AST, varying: Set[str]) -> bool:
        randomish = False
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name) and sub.id in varying:
                return False
            if isinstance(sub, ast.Call):
                name = call_name(sub) or ""
                head = name.split(".")[0]
                leaf = name.split(".")[-1]
                if head in ("random", "secrets") or \
                        leaf in ("random", "uniform", "backoff_delay",
                                 "jitter", "expovariate"):
                    randomish = True
            if isinstance(sub, ast.Attribute):
                chain = attr_chain(sub)
                if chain and chain[0] in varying:
                    return False
        return not randomish


class SilentSwallowChecker(Checker):
    name = "silent-swallow"
    ids = ("rpc/silent-swallow",)

    _BROAD = {"Exception", "BaseException"}

    def check_module(self, mod: SourceModule) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if not self._is_silent(node.body):
                continue
            f = mod.finding(node, "rpc/silent-swallow",
                            "broad except swallows every error silently — "
                            "narrow the exception type and leave a "
                            "log.debug breadcrumb")
            if f:
                findings.append(f)
        return findings

    def _is_broad(self, t: Optional[ast.AST]) -> bool:
        if t is None:
            return True                      # bare except:
        chain = attr_chain(t)
        if chain and chain[-1] in self._BROAD:
            return True
        if isinstance(t, ast.Tuple):
            return any(self._is_broad(el) for el in t.elts)
        return False

    @staticmethod
    def _is_silent(body) -> bool:
        if len(body) != 1:
            return False
        stmt = body[0]
        if isinstance(stmt, ast.Pass):
            return True
        return isinstance(stmt, ast.Expr) and \
            isinstance(stmt.value, ast.Constant) and \
            stmt.value.value is Ellipsis
