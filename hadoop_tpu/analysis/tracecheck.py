"""Tracing discipline: every span must reach ``finish()``.

An unfinished span never delivers (the collector, the flight recorder
and every receiver see nothing), silently punching a hole in the very
trace someone will later stare at — and if it was entered as the active
span, it leaks the contextvar slot too. The htrace-era bug class this
kills: a handler that finishes its span on the happy path but leaks it
on the exception edge.

``trace/span-not-finished`` flags a ``tracer.span(...)`` call that is
neither used as a context manager nor guaranteed to be finished:

- OK: ``with tracer.span(...) as sp:`` (directly or via an assigned
  name later used in a ``with``) — ``__exit__`` finishes on every edge.
- OK: ``tracer.span(...).finish()`` — immediate fire-and-forget marker.
- OK: the span object ESCAPES the creating function (passed as an
  argument, returned, yielded, stored on an object) — a long-lived
  span finished elsewhere; annotate intent at the handoff site.
- Flagged: assigned to a local that is never ``finish()``ed.
- Flagged: finished only on the straight-line path while a statement
  that can raise sits between creation and the first ``finish()`` and
  no enclosing ``try`` guarantees the finish (``finally``, or an
  ``except``/``else`` arm finishing it) — the exception edge leaks.

Span-method calls (``add_kv``/``annotate``/``context``) and argument-
free builtins (``str``/``int``/``len``/``repr``/``format``/``round``)
between creation and finish are treated as non-raising.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from hadoop_tpu.analysis.core import (Checker, Finding, SourceModule,
                                      attr_chain, call_name)

_SAFE_BUILTINS = {"str", "int", "float", "len", "repr", "format", "round",
                  "bool"}
_SPAN_METHODS = {"add_kv", "annotate", "context", "duration_ms"}


def _is_span_call(node: ast.Call) -> bool:
    """``<something tracer-ish>.span(...)``: the attribute is ``span``
    and the receiver chain mentions a tracer (``self.tracer``,
    ``tracer``, ``self._tracer``, ``global_tracer()``)."""
    if not (isinstance(node.func, ast.Attribute) and
            node.func.attr == "span"):
        return False
    recv = node.func.value
    chain = attr_chain(recv)
    if chain is not None:
        return any("tracer" in part for part in chain)
    if isinstance(recv, ast.Call):
        name = call_name(recv) or ""
        return "tracer" in name
    return False


class SpanFinishChecker(Checker):
    name = "trace-span-finish"
    ids = ("trace/span-not-finished",)

    def check_module(self, mod: SourceModule) -> List[Finding]:
        findings: List[Finding] = []
        for func in ast.walk(mod.tree):
            if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(mod, func, findings)
        return findings

    # ------------------------------------------------------------ per-func

    def _check_function(self, mod: SourceModule, func, findings) -> None:
        # calls already blessed: inside a with-item, or .finish()ed
        # directly on the call result
        in_with: Set[int] = set()
        direct_finished: Set[int] = set()
        for node in ast.walk(func):
            if isinstance(node, ast.With):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call) and _is_span_call(sub):
                            in_with.add(id(sub))
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "finish" and \
                    isinstance(node.func.value, ast.Call) and \
                    _is_span_call(node.func.value):
                direct_finished.add(id(node.func.value))

        # name -> (assign stmt, span call); only simple single-name
        # targets are tracked (anything fancier counts as an escape)
        assigned: Dict[str, Tuple[ast.stmt, ast.Call]] = {}
        bare: List[ast.Call] = []
        for node in ast.walk(func):
            if not (isinstance(node, ast.Call) and _is_span_call(node)):
                continue
            if id(node) in in_with or id(node) in direct_finished:
                continue
            holder = self._assignment_of(func, node)
            if holder is None:
                bare.append(node)
            else:
                name, stmt = holder
                if name is None:
                    continue  # attribute/subscript target: escapes
                assigned[name] = (stmt, node)

        for node in bare:
            f = mod.finding(node, "trace/span-not-finished",
                            "span is neither a context manager nor "
                            "finish()ed — it will never be delivered")
            if f:
                findings.append(f)

        for name, (stmt, node) in assigned.items():
            verdict = self._analyse_name(func, name, stmt)
            if verdict is not None:
                f = mod.finding(node, "trace/span-not-finished", verdict)
                if f:
                    findings.append(f)

    @staticmethod
    def _assignment_of(func, call: ast.Call
                       ) -> Optional[Tuple[Optional[str], ast.stmt]]:
        """The statement assigning this call, if any. Returns
        (name, stmt) for ``x = tracer.span(...)``; (None, stmt) for a
        non-name target (treated as escaping)."""
        def holds(value) -> bool:
            # the call itself, or nested in a conditional expression
            # (``cm = tracer.span(...) if ctx else nullcontext()``)
            return value is not None and \
                any(sub is call for sub in ast.walk(value))

        for stmt in ast.walk(func):
            if isinstance(stmt, ast.Assign) and holds(stmt.value):
                if len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name):
                    return stmt.targets[0].id, stmt
                return None, stmt
            if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)) and \
                    holds(getattr(stmt, "value", None)):
                if isinstance(stmt.target, ast.Name):
                    return stmt.target.id, stmt
                return None, stmt
        return None  # expression statement or nested expr → bare

    def _analyse_name(self, func, name: str, assign_stmt) -> Optional[str]:
        """None when the span named ``name`` is safely finished;
        else the finding message."""
        uses_with = False
        escapes = False
        finishes: List[ast.Call] = []
        for node in ast.walk(func):
            if isinstance(node, ast.With):
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Name) and expr.id == name:
                        uses_with = True
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == name and \
                        node.func.attr == "finish":
                    finishes.append(node)
                else:
                    # passed as an argument to any call → escapes
                    for arg in list(node.args) + \
                            [k.value for k in node.keywords]:
                        if isinstance(arg, ast.Name) and arg.id == name:
                            escapes = True
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                v = node.value
                if isinstance(v, ast.Name) and v.id == name:
                    escapes = True
                elif v is not None:
                    for sub in ast.walk(v):
                        if isinstance(sub, ast.Name) and sub.id == name:
                            escapes = True
            if isinstance(node, ast.Assign):
                # stored onto an object/container → escapes
                if isinstance(node.value, ast.Name) and \
                        node.value.id == name and \
                        any(not isinstance(t, ast.Name)
                            for t in node.targets):
                    escapes = True
        if uses_with or escapes:
            return None
        if not finishes:
            return (f"span '{name}' is never finish()ed on any path — "
                    "use 'with' or finish() in a finally")
        if self._finish_guarded(func, name):
            return None
        if self._raising_call_before_finish(func, assign_stmt, finishes,
                                            name):
            return (f"span '{name}' leaks on the exception edge: a call "
                    "that can raise sits between span() and finish() "
                    "with no finally/except finishing it — use 'with' "
                    "or a try/finally")
        return None

    # --------------------------------------------------------- path checks

    @staticmethod
    def _finish_guarded(func, name: str) -> bool:
        """True when some try statement finishes the span on its
        non-happy edges: a ``finally`` arm, or an ``except`` handler,
        containing ``name.finish()``."""
        def has_finish(stmts) -> bool:
            for s in stmts:
                for node in ast.walk(s):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "finish" and \
                            isinstance(node.func.value, ast.Name) and \
                            node.func.value.id == name:
                        return True
            return False

        for node in ast.walk(func):
            if isinstance(node, ast.Try):
                if has_finish(node.finalbody):
                    return True
                if node.handlers and all(
                        has_finish(h.body) for h in node.handlers) and \
                        has_finish(node.body + sum(
                            [h.body for h in node.handlers], []) +
                            node.orelse + node.finalbody):
                    # every except arm finishes AND the try covers the
                    # raising region (approximated: the finish exists)
                    return True
        return False

    def _raising_call_before_finish(self, func, assign_stmt, finishes,
                                    name: str) -> bool:
        """Scan the statements between the assignment and the first
        finish in the SAME statement list; any call that is not a span
        method or a safe builtin can raise past the finish."""
        parent_body = self._body_containing(func, assign_stmt)
        if parent_body is None:
            return False
        try:
            i = parent_body.index(assign_stmt)
        except ValueError:
            return False
        finish_lines = {f.lineno for f in finishes}
        for stmt in parent_body[i + 1:]:
            if any(f.lineno >= stmt.lineno and
                   f.lineno <= getattr(stmt, "end_lineno", stmt.lineno)
                   for f in finishes):
                return False  # reached a finish in-line
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and \
                        node.lineno not in finish_lines and \
                        not self._safe_call(node, name):
                    return True
        # fell off the list without reaching a finish: the finish lives
        # in a nested branch — conservatively fine (branch analysis is
        # out of scope; the no-finish and finally rules caught the
        # egregious cases)
        return False

    @staticmethod
    def _body_containing(func, stmt) -> Optional[list]:
        for node in ast.walk(func):
            for field in ("body", "orelse", "finalbody"):
                body = getattr(node, field, None)
                if isinstance(body, list) and stmt in body:
                    return body
            if isinstance(node, ast.Try):
                for h in node.handlers:
                    if stmt in h.body:
                        return h.body
        return None

    @staticmethod
    def _safe_call(node: ast.Call, name: str) -> bool:
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            if isinstance(recv, ast.Name) and recv.id == name and \
                    node.func.attr in _SPAN_METHODS | {"finish"}:
                return True
        if isinstance(node.func, ast.Name) and \
                node.func.id in _SAFE_BUILTINS:
            return True
        return False
