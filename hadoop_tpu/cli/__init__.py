"""Command-line layer: the `hadoop-tpu` dispatcher and its subcommands.

Parity with the reference's shell framework (ref: hadoop-common
src/main/bin/hadoop + hadoop-functions.sh (2,744 LoC), hdfs/yarn/mapred
scripts) — one console entry point dispatching to fs shell, admin tools,
daemons, and jobs, with GenericOptionsParser-style -D/-conf/-fs handling
(ref: util/GenericOptionsParser.java).
"""
