"""DFSAdmin + Fsck: `hadoop-tpu dfsadmin` / `hadoop-tpu fsck`.

Parity with the reference admin tools (ref: hadoop-hdfs
hdfs/tools/DFSAdmin.java:112, hdfs/tools/DFSck.java:75; server support
NamenodeFsck.java): cluster report, safemode control, checkpointing,
quota management, node admin, HA transitions, and a namespace health
walk that
classifies every block as healthy / under-replicated / corrupt /
missing.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.conf.keys import FS_DEFAULT_FS
from hadoop_tpu.fs.filesystem import FileSystem, Path
from hadoop_tpu.io import erasurecode as ec


class DFSAdmin:
    """Ref: hdfs/tools/DFSAdmin.java."""

    def __init__(self, conf: Optional[Configuration] = None, out=None):
        self.conf = conf or Configuration()
        self.out = out or sys.stdout
        self._fs = None

    def _print(self, *args) -> None:
        print(*args, file=self.out)

    def fs(self):
        if self._fs is None:
            uri = self.conf.get(FS_DEFAULT_FS) or ""
            self._fs = FileSystem.get(uri, self.conf)
            if not hasattr(self._fs, "client"):
                raise ValueError(
                    f"fs.defaultFS ({uri or 'unset'}) is not a DFS — pass "
                    f"-fs htpu://host:port")
        return self._fs

    def nn(self):
        return self.fs().client.nn

    def close(self) -> None:
        if self._fs is not None:
            self._fs.close()

    def run(self, argv: List[str]) -> int:
        if not argv:
            self._print("Usage: hadoop-tpu dfsadmin -<command> [args]")
            return 1
        cmd = argv[0].lstrip("-")
        handler = getattr(self, f"cmd_{cmd}", None)
        if handler is None:
            self._print(f"dfsadmin: unknown command -{cmd}")
            return 1
        try:
            return handler(argv[1:]) or 0
        except (IndexError, KeyError) as e:
            # only an EMPTY argv slice is an argument error here — a
            # KeyError from deep in the client/wire path must surface,
            # not masquerade as bad CLI usage
            import traceback
            tb = traceback.extract_tb(e.__traceback__)
            if any("hadoop_tpu/cli/" not in (fr.filename or "")
                   for fr in tb[1:]):
                raise
            self._print(f"dfsadmin -{cmd}: missing or malformed arguments")
            return 1
        except (OSError, ValueError) as e:
            self._print(f"dfsadmin -{cmd}: {e}")
            return 1

    # ------------------------------------------------------------- commands

    def cmd_report(self, args: List[str]) -> int:
        stats = self.nn().get_stats()
        self._print(f"Files: {stats['files']}  Blocks: {stats['blocks']}  "
                    f"Under-replicated: {stats['under_replicated']}")
        self._print(f"Safemode: {stats['safemode']}  "
                    f"Txid: {stats['txid']}  Leases: {stats['leases']}")
        nodes = self.nn().get_datanode_report("all")
        self._print(f"\nDatanodes ({len(nodes)}):")
        for d in nodes:
            self._print(
                f"  {d['u'][:8]} {d['h']}:{d['xp']} [{d['st']}] "
                f"type={d.get('sty', 'DISK')} blocks={d.get('nblk', 0)} "
                f"used={d.get('used', 0)}")
        return 0

    def cmd_safemode(self, args: List[str]) -> int:
        action = args[0] if args else "get"
        on = self.nn().set_safemode(action)
        self._print(f"Safe mode is {'ON' if on else 'OFF'}")
        return 0

    def cmd_saveNamespace(self, args: List[str]) -> int:
        path = self.nn().save_namespace()
        self._print(f"Saved namespace image: {path}")
        return 0

    def cmd_rollEdits(self, args: List[str]) -> int:
        # save_namespace rolls the edit segment as part of checkpointing.
        self.nn().save_namespace()
        self._print("Edit log rolled")
        return 0

    def cmd_setQuota(self, args: List[str]) -> int:
        quota = int(args[0])
        for path in args[1:]:
            self.nn().set_quota(path, quota, -1)
        return 0

    def cmd_clrQuota(self, args: List[str]) -> int:
        for path in args:
            self.nn().set_quota(path, -1, -1)
        return 0

    def cmd_setSpaceQuota(self, args: List[str]) -> int:
        quota = int(args[0])
        for path in args[1:]:
            self.nn().set_quota(path, -1, quota)
        return 0

    def cmd_decommission(self, args: List[str]) -> int:
        for uuid in args:
            self.nn().decommission_datanode(uuid)
            self._print(f"Decommissioning {uuid}")
        return 0

    def cmd_maintenance(self, args: List[str]) -> int:
        action, uuid = args[0], args[1]
        if action == "start":
            self.nn().start_maintenance(uuid)
        else:
            self.nn().stop_maintenance(uuid)
        return 0

    def cmd_allowSnapshot(self, args: List[str]) -> int:
        self.nn().allow_snapshot(args[0])
        self._print(f"Allowing snapshot on {args[0]} succeeded")
        return 0

    def cmd_disallowSnapshot(self, args: List[str]) -> int:
        self.nn().disallow_snapshot(args[0])
        return 0

    def cmd_setStoragePolicy(self, args: List[str]) -> int:
        path, policy = args[0], args[1]
        self.nn().set_storage_policy(path, policy)
        return 0

    def cmd_getStoragePolicy(self, args: List[str]) -> int:
        self._print(self.nn().get_storage_policy(args[0]))
        return 0

    def cmd_setECPolicy(self, args: List[str]) -> int:
        path, policy = args[0], args[1]
        self.nn().set_ec_policy(path, policy)
        self._print(f"Set {policy} erasure coding policy on {path}")
        return 0

    def cmd_listECPolicies(self, args: List[str]) -> int:
        for p in self.nn().get_ec_policies():
            self._print(f"{p['name']}: {p['codec']} k={p['k']} m={p['m']} "
                        f"cell={p['cell']}")
        return 0

    # HA ------------------------------------------------------------------

    def _ha_proxy(self, addr_spec: str):
        from hadoop_tpu.ipc import Client, get_proxy
        from hadoop_tpu.util.misc import parse_addr_list
        addr = parse_addr_list(addr_spec)[0]
        client = Client(self.conf)
        return get_proxy("HAServiceProtocol", addr, client=client), client

    def cmd_transitionToActive(self, args: List[str]) -> int:
        proxy, client = self._ha_proxy(args[0])
        try:
            proxy.transition_to_active()
            self._print(f"{args[0]} is now active")
        finally:
            client.stop()
        return 0

    def cmd_transitionToStandby(self, args: List[str]) -> int:
        proxy, client = self._ha_proxy(args[0])
        try:
            proxy.transition_to_standby()
            self._print(f"{args[0]} is now standby")
        finally:
            client.stop()
        return 0

    def cmd_getServiceState(self, args: List[str]) -> int:
        proxy, client = self._ha_proxy(args[0])
        try:
            self._print(proxy.get_ha_status()["state"])
        finally:
            client.stop()
        return 0


class Fsck:
    """Namespace health checker. Ref: hdfs/tools/DFSck.java:75 +
    server/namenode/NamenodeFsck.java — walks the tree and classifies
    every block's replica health."""

    def __init__(self, conf: Optional[Configuration] = None, out=None):
        self.conf = conf or Configuration()
        self.out = out or sys.stdout
        self._fs = None

    def _print(self, *args) -> None:
        print(*args, file=self.out)

    def fs(self):
        if self._fs is None:
            uri = self.conf.get(FS_DEFAULT_FS) or ""
            self._fs = FileSystem.get(uri, self.conf)
            if not hasattr(self._fs, "client"):
                raise ValueError(
                    f"fs.defaultFS ({uri or 'unset'}) is not a DFS — pass "
                    f"-fs htpu://host:port")
        return self._fs

    def close(self) -> None:
        if self._fs is not None:
            self._fs.close()

    def run(self, argv: List[str]) -> int:
        non_flags = [a for a in argv if not a.startswith("-")]
        path = non_flags[0] if non_flags else "/"
        verbose = "-files" in argv or "-blocks" in argv
        stats = {"files": 0, "dirs": 0, "bytes": 0, "blocks": 0,
                 "healthy": 0, "under": 0, "corrupt": 0, "missing": 0}
        try:
            nn = self.fs().client.nn
        except ValueError as e:
            self._print(f"fsck: {e}")
            return 1
        stack = [path]
        while stack:
            p = stack.pop()
            for st in nn.listing(p):
                if st["d"]:
                    stats["dirs"] += 1
                    stack.append(st["p"])
                    continue
                stats["files"] += 1
                stats["bytes"] += st["len"]
                self._check_file(nn, st, stats, verbose)
        healthy = stats["corrupt"] == 0 and stats["missing"] == 0
        self._print(f"\nStatus: {'HEALTHY' if healthy else 'CORRUPT'}")
        self._print(f" Total files:\t{stats['files']}")
        self._print(f" Total dirs:\t{stats['dirs']}")
        self._print(f" Total size:\t{stats['bytes']} B")
        self._print(f" Total blocks:\t{stats['blocks']}")
        self._print(f" Healthy blocks:\t{stats['healthy']}")
        self._print(f" Under-replicated:\t{stats['under']}")
        self._print(f" Corrupt blocks:\t{stats['corrupt']}")
        self._print(f" Missing blocks:\t{stats['missing']}")
        return 0 if healthy else 1

    def _check_file(self, nn, st, stats, verbose: bool) -> None:
        info = nn.get_block_locations(st["p"], 0, 1 << 62)
        line = [f"{st['p']} {st['len']} bytes, {len(info['blocks'])} "
                f"block(s):"]
        for bw in info["blocks"]:
            stats["blocks"] += 1
            n_locs = len(bw["locs"])
            if bw.get("ec"):
                policy = ec.get_policy(bw["ec"])
                if len(set(bw.get("idx") or [])) < policy.k:
                    stats["missing"] += 1
                    line.append(" MISSING(striped)")
                elif n_locs < policy.num_units:
                    stats["under"] += 1
                else:
                    stats["healthy"] += 1
                continue
            expected = st.get("rep", 1)
            if n_locs == 0:
                stats["missing"] += 1
                line.append(f" MISSING blk_{bw['b']['id']}")
            elif bw.get("cor"):
                stats["corrupt"] += 1
            elif n_locs < expected:
                stats["under"] += 1
                line.append(f" Under replicated blk_{bw['b']['id']} "
                            f"({n_locs}/{expected})")
            else:
                stats["healthy"] += 1
        if verbose or len(line) > 1:
            self._print("".join(line))
