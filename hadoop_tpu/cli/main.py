"""`hadoop-tpu` — the single dispatcher entry point.

Parity with the reference's shell scripts (ref: hadoop-common
src/main/bin/hadoop + hadoop-functions.sh, hdfs/yarn/mapred CLIs):

  hadoop-tpu fs -ls /                      filesystem shell
  hadoop-tpu dfsadmin -report              DFS administration
  hadoop-tpu fsck /path                    namespace health check
  hadoop-tpu balancer [-threshold 0.1]     rebalance block placement
  hadoop-tpu mover [path]                  satisfy storage policies
  hadoop-tpu namenode|datanode|journalnode daemon launchers
  hadoop-tpu rm|nodeagent                  resource-manager daemons
  hadoop-tpu historyserver|kms|httpfs|router|registry   more daemons
  hadoop-tpu serve --checkpoint URI --preset NAME   inference replica
  hadoop-tpu autoscale --registry H:P --service N   serving SLO controller
  hadoop-tpu doctor --namenode-http H:P [--registry H:P]   fleet doctor
  hadoop-tpu job -submit ...               MapReduce job control
  hadoop-tpu distcp SRC DST ...            distributed copy
  hadoop-tpu streaming --mapper CMD ...    external-process jobs
  hadoop-tpu archive SRC DST.har           create a har archive
  hadoop-tpu sls|gridmix|rumen|dynamometer simulators / replay tools\n  hadoop-tpu fs2img EXTERNAL DFS_ROOT --fs URI   mount external data as PROVIDED storage\n  hadoop-tpu resourceestimator TRACE       size a recurring job's reservation
  hadoop-tpu oiv|oev --name-dir DIR        offline image/edits viewers
  hadoop-tpu lint [PATHS] [--baseline F]   tpulint static analysis
  hadoop-tpu version

Generic options (before the subcommand args, ref:
util/GenericOptionsParser.java): -D key=value, -conf file.xml, -fs uri.
"""

from __future__ import annotations

import sys
from typing import List, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.conf.keys import (DFS_NAMENODE_RPC_ADDRESS,
                                  DFS_NAMENODE_RPC_ADDRESS_DEFAULT,
                                  FS_DEFAULT_FS, FS_DEFAULT_FS_DEFAULT)

VERSION = "0.1.0"


def parse_generic_options(conf: Configuration,
                          argv: List[str]) -> List[str]:
    """Consume -D/-conf/-fs prefix options into ``conf``; returns the
    remaining args. Ref: GenericOptionsParser."""
    rest: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "-D" and i + 1 < len(argv):
            key, _, val = argv[i + 1].partition("=")
            conf.set(key, val)
            i += 2
        elif a.startswith("-D") and "=" in a:
            key, _, val = a[2:].partition("=")
            conf.set(key, val)
            i += 1
        elif a == "-conf" and i + 1 < len(argv):
            conf.add_resource(argv[i + 1])
            i += 2
        elif a == "-fs" and i + 1 < len(argv):
            conf.set(FS_DEFAULT_FS, argv[i + 1])
            i += 2
        else:
            rest.append(a)
            i += 1
    return rest


def _run_daemon(service, conf: Configuration) -> int:
    import signal
    import threading
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    service.init(conf)
    service.start()
    try:
        while not stop.wait(1.0):
            pass
    finally:
        service.stop()
    return 0


def main(argv=None) -> int:
    try:
        return _main(argv)
    except BrokenPipeError:
        # Downstream pipe (e.g. `| head`) closed early — normal for CLIs.
        import os
        try:
            os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        except OSError:
            pass
        return 0


def _main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "help"):
        print(__doc__)
        return 0
    cmd, *rest = argv
    conf = Configuration()
    rest = parse_generic_options(conf, rest)

    if cmd == "version":
        print(f"hadoop-tpu {VERSION}")
        return 0
    if cmd == "fs":
        from hadoop_tpu.cli.shell import FsShell
        shell = FsShell(conf)
        try:
            return shell.run(rest)
        finally:
            shell.close()
    if cmd == "dfsadmin":
        from hadoop_tpu.cli.dfsadmin import DFSAdmin
        admin = DFSAdmin(conf)
        try:
            return admin.run(rest)
        finally:
            admin.close()
    if cmd == "fsck":
        from hadoop_tpu.cli.dfsadmin import Fsck
        fsck = Fsck(conf)
        try:
            return fsck.run(rest)
        finally:
            fsck.close()
    if cmd == "balancer":
        from hadoop_tpu.dfs.balancer import Balancer
        from hadoop_tpu.util.misc import parse_addr_list
        threshold = 0.10
        if "-threshold" in rest:
            threshold = float(rest[rest.index("-threshold") + 1])
        addrs = parse_addr_list(conf.get(DFS_NAMENODE_RPC_ADDRESS,
                                         DFS_NAMENODE_RPC_ADDRESS_DEFAULT))
        bal = Balancer(addrs, conf, threshold=threshold)
        try:
            stats = bal.run()
            print(f"Balancing complete: {stats}")
        finally:
            bal.close()
        return 0
    if cmd == "mover":
        from hadoop_tpu.dfs.balancer import Mover
        from hadoop_tpu.util.misc import parse_addr_list
        addrs = parse_addr_list(conf.get(DFS_NAMENODE_RPC_ADDRESS,
                                         DFS_NAMENODE_RPC_ADDRESS_DEFAULT))
        mover = Mover(addrs, conf)
        try:
            stats = mover.run(rest[0] if rest else "/")
            print(f"Mover complete: {stats}")
        finally:
            mover.close()
        return 0
    if cmd == "namenode":
        from hadoop_tpu.dfs.namenode import NameNode
        return _run_daemon(NameNode(conf), conf)
    if cmd == "datanode":
        from hadoop_tpu.dfs.datanode import DataNode
        return _run_daemon(DataNode(conf), conf)
    if cmd == "journalnode":
        from hadoop_tpu.dfs.qjournal import JournalNode
        return _run_daemon(JournalNode(conf), conf)
    if cmd == "rm":
        from hadoop_tpu.yarn.rm import ResourceManager
        return _run_daemon(ResourceManager(conf), conf)
    if cmd == "nodeagent":
        from hadoop_tpu.yarn.nm import NodeAgent
        from hadoop_tpu.util.misc import parse_addr_list
        addrs = parse_addr_list(conf.get(
            "yarn.resourcemanager.address", "127.0.0.1:8032"))
        return _run_daemon(NodeAgent(conf, rm_addr=addrs[0]), conf)
    if cmd == "historyserver":
        from hadoop_tpu.mapreduce.historyserver import JobHistoryServer
        return _run_daemon(JobHistoryServer(
            conf, conf.get(FS_DEFAULT_FS, FS_DEFAULT_FS_DEFAULT)), conf)
    if cmd == "kms":
        from hadoop_tpu.crypto.kms import KMSServer
        return _run_daemon(KMSServer(conf), conf)
    if cmd == "httpfs":
        from hadoop_tpu.dfs.httpfs import HttpFSServer
        return _run_daemon(HttpFSServer(
            conf, conf.get(FS_DEFAULT_FS, FS_DEFAULT_FS_DEFAULT)), conf)
    if cmd == "router":
        from hadoop_tpu.dfs.router import Router
        return _run_daemon(Router(conf), conf)
    if cmd == "registry":
        from hadoop_tpu.registry import RegistryServer
        return _run_daemon(RegistryServer(conf), conf)
    if cmd == "lint":
        # tpulint: AST static analysis for lock discipline, jit
        # retracing hazards, and RPC timeout hygiene (hadoop_tpu
        # .analysis) — the findbugs-in-CI lane of the reference
        from hadoop_tpu.analysis.__main__ import main as lint_main
        return lint_main(rest)
    if cmd == "serve":
        # one serving replica: continuous-batching decode fed from a DFS
        # checkpoint (hadoop_tpu.serving) — the YARN service packaging
        # launches this same entry point per container
        from hadoop_tpu.serving.service import replica_main
        return replica_main(rest, conf)
    if cmd == "autoscale":
        # the serving fleet's SLO controller: scrapes the registry +
        # every replica's /prom, grows/shrinks the fleet against
        # conf-keyed TTFT/backlog SLOs (advise mode without --rm/--app)
        from hadoop_tpu.serving.autoscale.__main__ import autoscaler_main
        return autoscaler_main(rest, conf)
    if cmd == "doctor":
        # the fleet doctor: cross-daemon trace assembly + statistical
        # slow-node detection over every daemon's /ws/v1 surfaces
        from hadoop_tpu.obs.doctor import doctor_main
        return doctor_main(rest, conf)
    if cmd == "job":
        # ref: mapred job -list/-status/-kill
        from hadoop_tpu.util.misc import parse_addr_list
        from hadoop_tpu.yarn.client import YarnClient
        from hadoop_tpu.yarn.records import ApplicationId
        rm = parse_addr_list(conf.get("yarn.resourcemanager.address",
                                      "127.0.0.1:8032"))[0]
        yc = YarnClient(rm, conf)
        try:
            if rest[:1] == ["-list"] or not rest:
                for rep in yc.list_applications():
                    print(f"{rep.app_id}\t{rep.name}\t{rep.state}\t"
                          f"{rep.queue}")
            elif rest[:1] == ["-status"]:
                rep = yc.application_report(ApplicationId.parse(rest[1]))
                print(f"{rep.app_id} {rep.state} final={rep.final_status} "
                      f"diag={rep.diagnostics!r}")
            elif rest[:1] == ["-kill"]:
                yc.kill_application(ApplicationId.parse(rest[1]))
                print(f"killed {rest[1]}")
            else:
                print("usage: job -list | -status APPID | -kill APPID",
                      file=sys.stderr)
                return 2
        finally:
            yc.close()
        return 0
    if cmd == "cacheadmin":
        # ref: hdfs cacheadmin — -addDirective/-listDirectives/-remove
        from hadoop_tpu.fs import FileSystem
        fs = FileSystem.get(conf.get(FS_DEFAULT_FS, FS_DEFAULT_FS_DEFAULT), conf)
        try:
            if rest[:1] == ["-addDirective"]:
                print(fs.add_cache_directive(rest[1]))
            elif rest[:1] == ["-removeDirective"]:
                print(fs.remove_cache_directive(int(rest[1])))
            elif rest[:1] == ["-listDirectives"] or not rest:
                for did, path in sorted(
                        fs.list_cache_directives().items()):
                    print(f"{did}\t{path}")
            else:
                print("usage: cacheadmin -addDirective PATH | "
                      "-removeDirective ID | -listDirectives",
                      file=sys.stderr)
                return 2
        finally:
            fs.close()
        return 0
    if cmd == "crypto":
        # ref: hdfs crypto — -createZone/-listZones
        from hadoop_tpu.fs import FileSystem
        fs = FileSystem.get(conf.get(FS_DEFAULT_FS, FS_DEFAULT_FS_DEFAULT), conf)
        try:
            if rest[:1] == ["-createZone"]:
                # -createZone -keyName K PATH
                key = rest[rest.index("-keyName") + 1]
                path = rest[-1]
                print(fs.create_encryption_zone(path, key))
            elif rest[:1] == ["-listZones"] or not rest:
                for path, key in sorted(
                        fs.list_encryption_zones().items()):
                    print(f"{path}\t{key}")
            else:
                print("usage: crypto -createZone -keyName K PATH | "
                      "-listZones", file=sys.stderr)
                return 2
        finally:
            fs.close()
        return 0
    if cmd == "distcp":
        from hadoop_tpu.tools.distcp import main as distcp_main
        return distcp_main(rest)
    if cmd == "streaming":
        from hadoop_tpu.tools.streaming import main as streaming_main
        return streaming_main(rest)
    if cmd == "archive":
        from hadoop_tpu.tools.archive import main as archive_main
        return archive_main(rest)
    if cmd == "sls":
        from hadoop_tpu.tools.sls import main as sls_main
        return sls_main(rest)
    if cmd == "gridmix":
        from hadoop_tpu.tools.gridmix import main as gridmix_main
        return gridmix_main(rest)
    if cmd == "rumen":
        from hadoop_tpu.tools.rumen import main as rumen_main
        return rumen_main(rest)
    if cmd == "dynamometer":
        from hadoop_tpu.tools.dynamometer import main as dyn_main
        return dyn_main(rest)
    if cmd == "fs2img":
        from hadoop_tpu.tools.fs2img import main as fs2img_main
        return fs2img_main(rest)
    if cmd == "resourceestimator":
        from hadoop_tpu.tools.resourceestimator import main as re_main
        return re_main(rest)
    if cmd == "oiv":
        from hadoop_tpu.cli.oiv import main_oiv
        return main_oiv(rest)
    if cmd == "oev":
        from hadoop_tpu.cli.oiv import main_oev
        return main_oev(rest)
    print(f"hadoop-tpu: unknown command {cmd!r}; try `hadoop-tpu help`",
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
