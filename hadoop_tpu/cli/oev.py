"""Offline Edits Viewer entry point (see hadoop_tpu.cli.oiv.dump_edits;
ref: tools/offlineEditsViewer/OfflineEditsViewer.java)."""

import sys

from hadoop_tpu.cli.oiv import main_oev

if __name__ == "__main__":
    sys.exit(main_oev())
