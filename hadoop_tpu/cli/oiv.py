"""Offline Image Viewer + Offline Edits Viewer.

Parity with the reference tools (ref: hadoop-hdfs tools/
offlineImageViewer/OfflineImageViewerPB.java and tools/
offlineEditsViewer/OfflineEditsViewer.java): inspect NameNode
persistence WITHOUT a running NameNode — the image dumps as one JSON
object per inode, the edit segments as one JSON object per transaction.

  python -m hadoop_tpu.cli.oiv  --name-dir /path/to/nn/name
  python -m hadoop_tpu.cli.oev  --name-dir /path/to/nn/name [--from TXID]
"""

from __future__ import annotations

import json
import os
import sys
from typing import Optional


def dump_image(name_dir: str, out=sys.stdout) -> int:
    """One JSON line per inode (path, type, attrs). Returns inode count."""
    from hadoop_tpu.dfs.namenode.fsimage import FSImage
    from hadoop_tpu.dfs.namenode.inodes import INodeDirectory, INodeFile
    image = FSImage(os.path.join(name_dir, "image"))
    loaded = image.load()
    if loaded is None:
        print(json.dumps({"error": "no image found"}), file=out)
        return 0
    txid, fsdir, extra = loaded
    print(json.dumps({"image_txid": txid,
                      **{k: v for k, v in extra.items()
                         if isinstance(v, (int, str))}}), file=out)
    count = 0

    def walk(node, path: str) -> None:
        nonlocal count
        count += 1
        if isinstance(node, INodeFile):
            print(json.dumps({
                "path": path or "/", "type": "FILE",
                "replication": node.replication,
                "blocks": [{"id": b.block_id, "gs": b.gen_stamp,
                            "len": b.num_bytes} for b in node.blocks],
                "length": node.length(),
                "owner": getattr(node, "owner", ""),
                "uc": node.under_construction,
            }), file=out)
        else:
            print(json.dumps({
                "path": path or "/", "type": "DIRECTORY",
                "children": len(node.children),
                "owner": getattr(node, "owner", ""),
                "snapshots": sorted((node.snapshots or {}).keys())
                if isinstance(node, INodeDirectory) else [],
            }), file=out)
            for name, child in sorted(node.children.items()):
                walk(child, f"{path}/{name}")

    walk(fsdir.root, "")
    return count


def dump_edits(name_dir: str, from_txid: int = 1, out=sys.stdout) -> int:
    """One JSON line per edit transaction. Returns transaction count."""
    from hadoop_tpu.dfs.namenode.editlog import FileJournalManager
    fjm = FileJournalManager(os.path.join(name_dir, "edits"))
    n = 0
    for rec in fjm.read_edits(from_txid):
        print(json.dumps(rec, default=str), file=out)
        n += 1
    return n


def main_oiv(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="oiv")
    ap.add_argument("--name-dir", required=True)
    args = ap.parse_args(argv)
    dump_image(args.name_dir)
    return 0


def main_oev(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="oev")
    ap.add_argument("--name-dir", required=True)
    ap.add_argument("--from", dest="from_txid", type=int, default=1)
    args = ap.parse_args(argv)
    dump_edits(args.name_dir, args.from_txid)
    return 0


if __name__ == "__main__":
    sys.exit(main_oiv())
