"""FsShell: the `hadoop-tpu fs` command family.

Parity with the reference (ref: hadoop-common fs/FsShell.java:45 and the
fs/shell/ command classes: Ls, Mkdir, CopyCommands, Delete, Tail, Count,
SetReplication, XAttrCommands, AclCommands, SnapshotCommands): each
``-command`` maps to one method; paths without a scheme resolve against
``fs.defaultFS``.
"""

from __future__ import annotations

import logging
import sys
import time
from typing import List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.conf.keys import (FS_DEFAULT_FS, FS_DEFAULT_FS_DEFAULT,
                                  FS_TRASH_INTERVAL,
                                  FS_TRASH_INTERVAL_DEFAULT)
from hadoop_tpu.fs.filesystem import FileSystem, Path
from hadoop_tpu.fs.trash import Trash

log = logging.getLogger(__name__)


def _fmt_size(n: int) -> str:
    for unit in ("", "K", "M", "G", "T"):
        if n < 1024 or unit == "T":
            return f"{n:.1f}{unit}" if unit else str(n)
        n /= 1024.0
    return str(n)


def _perm_str(st) -> str:
    kind = "d" if st.is_dir else "-"
    bits = ""
    for shift in (6, 3, 0):
        p = (st.permission >> shift) & 7
        bits += ("r" if p & 4 else "-") + ("w" if p & 2 else "-") + \
            ("x" if p & 1 else "-")
    return kind + bits


class FsShell:
    """Ref: fs/FsShell.java — run() returns a process exit code."""

    def __init__(self, conf: Optional[Configuration] = None, out=None):
        self.conf = conf or Configuration()
        self.out = out or sys.stdout
        self._fs_cache = {}

    def _fs(self, path: str) -> FileSystem:
        p = Path(path)
        if p.scheme == "file" and not path.startswith("file:"):
            # presence probe, not a defaulted read: only an EXPLICIT
            # fs.defaultFS redirects schemeless paths
            default = self.conf.get(FS_DEFAULT_FS) or ""
            if default:
                key = default
                if key not in self._fs_cache:
                    self._fs_cache[key] = FileSystem.get(default, self.conf)
                return self._fs_cache[key]
        key = f"{p.scheme}://{p.authority}"
        if key not in self._fs_cache:
            self._fs_cache[key] = FileSystem.get(path, self.conf)
        return self._fs_cache[key]

    def _print(self, *args) -> None:
        print(*args, file=self.out)

    def close(self) -> None:
        for fs in self._fs_cache.values():
            try:
                fs.close()
            except (OSError, ValueError) as e:
                log.debug("fs close failed: %s", e)

    # ----------------------------------------------------------------- run

    def run(self, argv: List[str]) -> int:
        if not argv or not argv[0].startswith("-"):
            self._print("Usage: hadoop-tpu fs -<command> [args]")
            return 1
        cmd = argv[0].lstrip("-")
        handler = getattr(self, f"cmd_{cmd.replace('-', '_')}", None)
        if handler is None:
            self._print(f"fs: unknown command -{cmd}")
            return 1
        try:
            return handler(argv[1:]) or 0
        except (IndexError, KeyError) as e:
            # only an EMPTY argv slice is an argument error here — a
            # KeyError from deep in the client/wire path must surface,
            # not masquerade as bad CLI usage
            import traceback
            tb = traceback.extract_tb(e.__traceback__)
            if any("hadoop_tpu/cli/" not in (fr.filename or "")
                   for fr in tb[1:]):
                raise
            self._print(f"fs -{cmd}: missing or malformed arguments")
            return 1
        except (OSError, ValueError) as e:
            self._print(f"fs -{cmd}: {e}")
            return 1

    # ------------------------------------------------------------- commands

    def cmd_ls(self, args: List[str]) -> int:
        recursive = "-R" in args
        paths = [a for a in args if not a.startswith("-")] or ["/"]
        for path in paths:
            fs = self._fs(path)
            self._ls_one(fs, Path(path).path, recursive)
        return 0

    def _ls_one(self, fs, path: str, recursive: bool) -> None:
        entries = fs.list_status(path)
        self._print(f"Found {len(entries)} items")
        for st in entries:
            when = time.strftime("%Y-%m-%d %H:%M",
                                 time.localtime(st.mtime or 0))
            self._print(f"{_perm_str(st)} {st.replication or '-':>3} "
                        f"{st.owner:8} {st.group:8} {st.length:>10} "
                        f"{when} {st.path}")
        if recursive:
            for st in entries:
                if st.is_dir:
                    self._ls_one(fs, st.path, recursive)

    def cmd_lsr(self, args):
        return self.cmd_ls(["-R"] + args)

    def cmd_mkdir(self, args: List[str]) -> int:
        args = [a for a in args if a != "-p"]
        for path in args:
            self._fs(path).mkdirs(Path(path).path)
        return 0

    def cmd_put(self, args: List[str]) -> int:
        """-put <localsrc>... <dst>. Ref: CopyCommands.Put."""
        *srcs, dst = args
        fs = self._fs(dst)
        dstp = Path(dst).path
        many = len(srcs) > 1 or (fs.exists(dstp)
                                 and fs.get_file_status(dstp).is_dir)
        for src in srcs:
            target = f"{dstp.rstrip('/')}/{src.rsplit('/', 1)[-1]}" \
                if many else dstp
            with open(src, "rb") as inf, fs.create(target) as outf:
                while True:
                    chunk = inf.read(1 << 20)
                    if not chunk:
                        break
                    outf.write(chunk)
        return 0

    def cmd_get(self, args: List[str]) -> int:
        src, dst = args
        fs = self._fs(src)
        import os
        if os.path.isdir(dst):
            dst = os.path.join(dst, Path(src).name)
        with fs.open(Path(src).path) as inf, open(dst, "wb") as outf:
            while True:
                chunk = inf.read(1 << 20)
                if not chunk:
                    break
                outf.write(chunk)
        return 0

    def cmd_cat(self, args: List[str]) -> int:
        for path in args:
            fs = self._fs(path)
            with fs.open(Path(path).path) as f:
                data = f.read()
            self.out.write(data.decode("utf-8", "replace"))
        return 0

    def cmd_text(self, args):
        return self.cmd_cat(args)

    def cmd_tail(self, args: List[str]) -> int:
        path = args[-1]
        fs = self._fs(path)
        st = fs.get_file_status(Path(path).path)
        with fs.open(Path(path).path) as f:
            f.seek(max(0, st.length - 1024))
            self.out.write(f.read().decode("utf-8", "replace"))
        return 0

    def cmd_rm(self, args: List[str]) -> int:
        """-rm [-r] [-skipTrash] <path>...; trash by default when
        fs.trash.interval > 0 (ref: Delete.Rm + moveToTrash)."""
        recursive = "-r" in args or "-R" in args
        skip_trash = "-skipTrash" in args
        paths = [a for a in args if not a.startswith("-")]
        interval = self.conf.get_time_seconds(FS_TRASH_INTERVAL,
                                              FS_TRASH_INTERVAL_DEFAULT)
        for path in paths:
            fs = self._fs(path)
            p = Path(path).path
            if not recursive and fs.get_file_status(p).is_dir:
                self._print(f"rm: `{path}': Is a directory")
                return 1
            if interval > 0 and not skip_trash:
                loc = Trash(fs, interval).move_to_trash(p)
                self._print(f"Moved: '{path}' to trash at: {loc}")
            else:
                if not fs.delete(p, recursive=recursive):
                    self._print(f"rm: `{path}': No such file or directory")
                    return 1
                self._print(f"Deleted {path}")
        return 0

    def cmd_rmr(self, args):
        return self.cmd_rm(["-r"] + args)

    def cmd_expunge(self, args: List[str]) -> int:
        fs = self._fs(self.conf.get(FS_DEFAULT_FS, FS_DEFAULT_FS_DEFAULT))
        # expunge still needs a checkpoint period when trash is off
        # (interval default 0): fall back to one day explicitly
        interval = self.conf.get_time_seconds(FS_TRASH_INTERVAL,
                                              FS_TRASH_INTERVAL_DEFAULT)
        trash = Trash(fs, interval or 24 * 3600.0)
        trash.checkpoint()
        for gone in trash.expunge():
            self._print(f"Deleted trash checkpoint: {gone}")
        return 0

    def cmd_mv(self, args: List[str]) -> int:
        src, dst = args
        self._fs(src).rename(Path(src).path, Path(dst).path)
        return 0

    def cmd_cp(self, args: List[str]) -> int:
        src, dst = args
        sfs, dfs = self._fs(src), self._fs(dst)
        with sfs.open(Path(src).path) as inf, \
                dfs.create(Path(dst).path) as outf:
            while True:
                chunk = inf.read(1 << 20)
                if not chunk:
                    break
                outf.write(chunk)
        return 0

    def cmd_touchz(self, args: List[str]) -> int:
        for path in args:
            with self._fs(path).create(Path(path).path) as f:
                f.write(b"")
        return 0

    def cmd_stat(self, args: List[str]) -> int:
        for path in args:
            st = self._fs(path).get_file_status(Path(path).path)
            self._print(time.strftime("%Y-%m-%d %H:%M:%S",
                                      time.localtime(st.mtime or 0)))
        return 0

    def cmd_du(self, args: List[str]) -> int:
        human = "-h" in args
        paths = [a for a in args if not a.startswith("-")] or ["/"]
        for path in paths:
            fs = self._fs(path)
            for st in fs.list_status(Path(path).path):
                size = st.length
                if st.is_dir and hasattr(fs, "content_summary"):
                    size = fs.content_summary(st.path)["length"]
                self._print(f"{_fmt_size(size) if human else size:>12}  "
                            f"{st.path}")
        return 0

    def cmd_count(self, args: List[str]) -> int:
        for path in args:
            fs = self._fs(path)
            cs = fs.content_summary(Path(path).path)
            self._print(f"{cs['dirs']:>12} {cs['files']:>12} "
                        f"{cs['length']:>12} {path}")
        return 0

    def cmd_df(self, args: List[str]) -> int:
        fs = self._fs(args[0] if args else
                      self.conf.get(FS_DEFAULT_FS, FS_DEFAULT_FS_DEFAULT))
        stats = fs.client.nn.get_stats() if hasattr(fs, "client") else {}
        self._print(f"Filesystem stats: {stats}")
        return 0

    def cmd_setrep(self, args: List[str]) -> int:
        rep, path = int(args[0]), args[1]
        self._fs(path).set_replication(Path(path).path, rep)
        self._print(f"Replication {rep} set: {path}")
        return 0

    def cmd_chmod(self, args: List[str]) -> int:
        mode, path = args[0], args[1]
        self._fs(path).set_permission(Path(path).path, int(mode, 8))
        return 0

    def cmd_chown(self, args: List[str]) -> int:
        spec, path = args[0], args[1]
        owner, _, group = spec.partition(":")
        self._fs(path).set_owner(Path(path).path, owner, group)
        return 0

    def cmd_test(self, args: List[str]) -> int:
        """-test -e|-d|-f <path> — exit code is the answer."""
        flag, path = args[0], args[1]
        fs = self._fs(path)
        try:
            st = fs.get_file_status(Path(path).path)
        except FileNotFoundError:
            return 1
        if flag == "-d":
            return 0 if st.is_dir else 1
        if flag == "-f":
            return 0 if not st.is_dir else 1
        return 0

    # xattr / acl ---------------------------------------------------------

    def cmd_setfattr(self, args: List[str]) -> int:
        """-setfattr -n name [-v value] | -x name <path>."""
        if "-x" in args:
            name = args[args.index("-x") + 1]
            path = args[-1]
            self._fs(path).remove_xattr(Path(path).path, name)
            return 0
        name = args[args.index("-n") + 1]
        value = args[args.index("-v") + 1].encode() if "-v" in args else b""
        path = args[-1]
        self._fs(path).set_xattr(Path(path).path, name, value)
        return 0

    def cmd_getfattr(self, args: List[str]) -> int:
        path = args[-1]
        attrs = self._fs(path).get_xattrs(Path(path).path)
        self._print(f"# file: {path}")
        for name, value in sorted(attrs.items()):
            self._print(f'{name}="{value.decode("utf-8", "replace")}"')
        return 0

    def cmd_setfacl(self, args: List[str]) -> int:
        """-setfacl -m entries | -b <path>."""
        path = args[-1]
        if "-b" in args:
            self._fs(path).set_acl(Path(path).path, [])
            return 0
        entries = args[args.index("-m") + 1].split(",")
        self._fs(path).set_acl(Path(path).path, entries)
        return 0

    def cmd_getfacl(self, args: List[str]) -> int:
        path = args[-1]
        self._print(f"# file: {path}")
        for e in self._fs(path).get_acl(Path(path).path):
            self._print(e)
        return 0

    # snapshots -----------------------------------------------------------

    def cmd_createSnapshot(self, args: List[str]) -> int:
        path = args[0]
        name = args[1] if len(args) > 1 else f"s{int(time.time())}"
        loc = self._fs(path).create_snapshot(Path(path).path, name)
        self._print(f"Created snapshot {loc}")
        return 0

    def cmd_deleteSnapshot(self, args: List[str]) -> int:
        self._fs(args[0]).delete_snapshot(Path(args[0]).path, args[1])
        return 0

    def cmd_renameSnapshot(self, args: List[str]) -> int:
        self._fs(args[0]).rename_snapshot(Path(args[0]).path, args[1],
                                          args[2])
        return 0
