from hadoop_tpu.conf.configuration import Configuration, ConfigRegistry
from hadoop_tpu.conf import keys  # noqa: F401  — registers deprecations

__all__ = ["Configuration", "ConfigRegistry", "keys"]
