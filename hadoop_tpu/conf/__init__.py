from hadoop_tpu.conf.configuration import Configuration, ConfigRegistry

__all__ = ["Configuration", "ConfigRegistry"]
