"""Layered, typed key/value configuration.

Capability parity with the reference's ``conf/Configuration.java`` (3,968 LoC;
see SURVEY.md §5.6): default resources overlaid by site resources, ``${var}``
expansion (with environment fallback ``${env.VAR}``), a deprecation table that
maps old keys to new ones with warn-once semantics, typed getters, final
(unoverridable) properties, and live reconfiguration hooks
(ref: conf/ReconfigurableBase.java).

Differences from the reference, by design: resources are TOML-ish flat
``key = value`` text or JSON dicts rather than Hadoop XML — there is no XML
ecosystem to stay compatible with, and flat files diff cleanly.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import re
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple
from hadoop_tpu.util.annotations import audience, stability

log = logging.getLogger(__name__)

_VAR_PATTERN = re.compile(r"\$\{([^}$\s]+)\}")
_MAX_SUBST_DEPTH = 20

# Size suffixes accepted by get_size_bytes (ref: Configuration.getLongBytes /
# StringUtils.TraditionalBinaryPrefix).
_SIZE_SUFFIXES = {
    "k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40, "p": 1 << 50,
}
_TIME_SUFFIXES = {  # ref: Configuration.getTimeDuration
    "ns": 1e-9, "us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0,
}

_TRUE = {"true", "yes", "on", "1"}
_FALSE = {"false", "no", "off", "0"}

# Registry strict mode: opt in with conf.strict.keys=true and every
# set() of a key the generated registry doesn't know warns once — the
# runtime face of tpulint's conf-discipline family (a typo'd key is
# caught at the set, not three subsystems later when nothing reads it).
_STRICT_KEY = "conf.strict.keys"


def _strict_enabled(conf: "Configuration") -> bool:
    return conf.get_bool(_STRICT_KEY, False)


def _registry_knows(key: str) -> bool:
    """The generated registry (hadoop_tpu/conf/registry.py) accounts for
    ``key`` — as a concrete key, a dynamic-family pattern, or a
    deprecated spelling. A missing registry knows everything (partial
    checkouts must not warn on every set)."""
    try:
        from hadoop_tpu.conf import registry
    except ImportError:  # pragma: no cover - registry not generated yet
        return True
    if key == _STRICT_KEY or key in registry.KEYS:
        return True
    if ConfigRegistry.deprecation_for(key) is not None:
        return True
    from fnmatch import fnmatchcase
    return any(fnmatchcase(key, p) for p in registry.PATTERNS)


class DeprecationDelta:
    """One deprecated key and its replacement(s). Ref: Configuration.DeprecationDelta."""

    def __init__(self, old_key: str, new_keys: List[str], message: Optional[str] = None):
        self.old_key = old_key
        self.new_keys = list(new_keys)
        self.message = message or (
            f"{old_key} is deprecated. Instead, use {', '.join(new_keys)}"
        )
        self.warned = False


class ConfigRegistry:
    """Process-wide default resources + deprecation table.

    Ref: Configuration.addDefaultResource / Configuration.addDeprecations —
    statics on the Java class; here an explicit singleton so tests can reset it.
    """

    _lock = threading.Lock()
    _default_resources: List[Dict[str, str]] = []
    _deprecations: Dict[str, DeprecationDelta] = {}

    @classmethod
    def add_default_resource(cls, resource: Dict[str, str]) -> None:
        with cls._lock:
            cls._default_resources.append(dict(resource))

    @classmethod
    def add_deprecations(cls, deltas: List[DeprecationDelta]) -> None:
        with cls._lock:
            for d in deltas:
                cls._deprecations[d.old_key] = d

    @classmethod
    def deprecation_for(cls, key: str) -> Optional[DeprecationDelta]:
        return cls._deprecations.get(key)

    @classmethod
    def default_resources(cls) -> List[Dict[str, str]]:
        with cls._lock:
            return list(cls._default_resources)

    @classmethod
    def reset_for_tests(cls) -> None:
        """Back to the SHIPPED state: no default resources, and the
        tree's own deprecation table (conf/keys.py) re-registered fresh
        so warn-once flags reset too."""
        with cls._lock:
            cls._default_resources = []
            cls._deprecations = {}
        try:
            from hadoop_tpu.conf.keys import shipped_deprecations
        except ImportError:  # pragma: no cover - partial checkouts
            return
        cls.add_deprecations(shipped_deprecations())


@audience.public
@stability.stable
class Configuration:
    """Layered key/value store with typed access and variable expansion."""

    def __init__(self, other: Optional["Configuration"] = None,
                 load_defaults: bool = True):
        self._lock = threading.RLock()
        self._props: Dict[str, str] = {}
        self._finals: set = set()
        self._sources: Dict[str, str] = {}
        self._reconf_listeners: List[Callable[[str, Optional[str], Optional[str]], None]] = []
        self._strict_warned: set = set()  # strict-mode warn-once, per key
        if other is not None:
            with other._lock:
                self._props = dict(other._props)
                self._finals = set(other._finals)
                self._sources = dict(other._sources)
        elif load_defaults:
            for res in ConfigRegistry.default_resources():
                self._merge(res, source="default", respect_final=False)

    # ------------------------------------------------------------------ load

    def _merge(self, props: Dict[str, Any], source: str,
               respect_final: bool = True,
               final_keys: Optional[set] = None) -> None:
        # under the lock: reload/add_resource races locked readers
        # (to_dict/__iter__) — an unlocked overlay raised "dict changed
        # size during iteration" and could expose a half-applied
        # resource (values visible before their final markers)
        with self._lock:
            for k, v in props.items():
                k = self._handle_deprecation_on_set(k)
                if respect_final and k in self._finals:
                    log.warning("Ignoring override of final parameter "
                                "%s from %s", k, source)
                    continue
                if final_keys and k in final_keys:
                    self._finals.add(k)  # marker BEFORE the value lands
                self._props[k] = str(v)
                self._sources[k] = source

    def add_resource(self, resource, source: Optional[str] = None) -> None:
        """Overlay a resource: a dict, a JSON file path, or a flat key=value file.

        Flat format: one ``key = value`` per line, '#' comments, and an optional
        ``!final`` suffix marking the property final (ref: <final>true</final>).
        """
        if isinstance(resource, dict):
            self._merge(resource, source or "dict")
            return
        path = str(resource)
        finals: set = set()
        props: Dict[str, str] = {}
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        stripped = text.lstrip()
        if stripped.startswith("{"):
            props = {str(k): str(v) for k, v in json.loads(text).items()}
        else:
            for lineno, line in enumerate(text.splitlines(), 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if "=" not in line:
                    raise ValueError(f"{path}:{lineno}: expected 'key = value'")
                k, v = line.split("=", 1)
                k, v = k.strip(), v.strip()
                if v.endswith("!final"):
                    v = v[: -len("!final")].rstrip()
                    finals.add(k)
                props[k] = v
        self._merge(props, source or path, final_keys=finals)

    # ------------------------------------------------------- deprecation

    def _handle_deprecation_on_set(self, key: str) -> str:
        d = ConfigRegistry.deprecation_for(key)
        if d is None:
            return key
        if not d.warned:
            log.warning("%s", d.message)
            d.warned = True
        return d.new_keys[0] if d.new_keys else key

    def _resolve_keys(self, key: str) -> List[str]:
        """All storage keys this lookup key may live under (new names first)."""
        d = ConfigRegistry.deprecation_for(key)
        if d is None:
            return [key]
        if not d.warned:
            log.warning("%s", d.message)
            d.warned = True
        return d.new_keys + [key]

    # ------------------------------------------------------------ raw get/set

    def get_raw(self, key: str) -> Optional[str]:
        with self._lock:
            for k in self._resolve_keys(key):
                if k in self._props:
                    return self._props[k]
        return None

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        raw = self.get_raw(key)
        if raw is None:
            return default
        return self._substitute(raw)

    def get_trimmed(self, key: str, default: Optional[str] = None) -> Optional[str]:
        v = self.get(key, default)
        return v.strip() if isinstance(v, str) else v

    def set(self, key: str, value: Any, source: str = "programmatic") -> None:
        with self._lock:
            k = self._handle_deprecation_on_set(key)
            old = self._props.get(k)
            self._props[k] = str(value)
            self._sources[k] = source
            listeners = list(self._reconf_listeners)
        # outside the lock: the strict probe re-enters get_raw
        if k not in self._strict_warned and _strict_enabled(self) and \
                not _registry_knows(k):
            self._strict_warned.add(k)
            log.warning(
                "conf.strict.keys: set() of key %r that the conf "
                "registry does not know — a typo, or a new lever that "
                "needs `hadoop-tpu lint --write-conf-registry`", k)
        for cb in listeners:
            cb(k, old, str(value))

    def unset(self, key: str) -> None:
        with self._lock:
            for k in self._resolve_keys(key):
                self._props.pop(k, None)
                self._sources.pop(k, None)
                self._finals.discard(k)

    def set_if_unset(self, key: str, value: Any) -> None:
        if self.get_raw(key) is None:
            self.set(key, value)

    # -------------------------------------------------------- substitution

    def _substitute(self, value: str, depth: int = 0) -> str:
        """${var} expansion against other keys, then ${env.NAME}. Ref:
        Configuration.substituteVars (MAX_SUBST=20)."""
        if depth >= _MAX_SUBST_DEPTH or "${" not in value:
            return value

        def repl(m: re.Match) -> str:
            name = m.group(1)
            if name.startswith("env."):
                return os.environ.get(name[4:], m.group(0))
            with self._lock:
                inner = self._props.get(name)
            if inner is None:
                return m.group(0)
            return self._substitute(inner, depth + 1)

        return _VAR_PATTERN.sub(repl, value)

    # ------------------------------------------------------------ typed gets

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get_trimmed(key)
        if v is None or v == "":
            return default
        try:
            if v.lower().startswith("0x"):
                return int(v, 16)
            return int(v)
        except ValueError:
            raise ValueError(
                f"conf key {key!r}: invalid int value {v!r}") from None

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get_trimmed(key)
        return default if v is None or v == "" else float(v)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get_trimmed(key)
        if v is None or v == "":
            return default
        vl = v.lower()
        if vl in _TRUE:
            return True
        if vl in _FALSE:
            return False
        # loudly, naming the key: a silent fall-through to the default
        # turns "treu" into production-off and nobody ever finds out
        raise ValueError(
            f"conf key {key!r}: invalid boolean value {v!r} (accepted: "
            f"{'/'.join(sorted(_TRUE))} or {'/'.join(sorted(_FALSE))})")

    def get_size_bytes(self, key: str, default: int = 0) -> int:
        """'64m' → 67108864. Ref: Configuration.getLongBytes."""
        v = self.get_trimmed(key)
        if v is None or v == "":
            return default
        vl = v.lower()
        if vl[-1] in _SIZE_SUFFIXES and not vl[-1].isdigit():
            return int(float(vl[:-1]) * _SIZE_SUFFIXES[vl[-1]])
        return int(v)

    def get_time_seconds(self, key: str, default: float = 0.0) -> float:
        """'30s' / '5m' / '100ms' → seconds. Ref: Configuration.getTimeDuration."""
        v = self.get_trimmed(key)
        if v is None or v == "":
            return default
        vl = v.lower()
        for suf in sorted(_TIME_SUFFIXES, key=len, reverse=True):
            if vl.endswith(suf) and not vl[: -len(suf)] == "":
                head = vl[: -len(suf)]
                try:
                    return float(head) * _TIME_SUFFIXES[suf]
                except ValueError:
                    continue
        return float(vl)

    def get_list(self, key: str, default: Optional[List[str]] = None) -> List[str]:
        v = self.get_trimmed(key)
        if v is None or v == "":
            return list(default) if default else []
        return [s.strip() for s in v.split(",") if s.strip()]

    def get_range(self, key: str, default: str = "") -> List[int]:
        """'2000-2010,2020' → expanded int list. Ref: Configuration.getRange."""
        v = self.get_trimmed(key, default)
        out: List[int] = []
        if not v:
            return out
        for part in v.split(","):
            part = part.strip()
            if "-" in part:
                lo, hi = part.split("-", 1)
                out.extend(range(int(lo), int(hi) + 1))
            elif part:
                out.append(int(part))
        return out

    def get_class(self, key: str, default: Optional[type] = None) -> Optional[type]:
        """Resolve a dotted class name. Ref: Configuration.getClass."""
        v = self.get_trimmed(key)
        if v is None or v == "":
            return default
        mod, _, cls = v.rpartition(".")
        import importlib
        return getattr(importlib.import_module(mod), cls)

    # --------------------------------------------------------- introspection

    def get_property_source(self, key: str) -> Optional[str]:
        with self._lock:
            for k in self._resolve_keys(key):
                if k in self._sources:
                    return self._sources[k]
        return None

    def get_by_prefix(self, prefix: str) -> Dict[str, str]:
        """Ref: Configuration.getPropsWithPrefix (keys with prefix stripped)."""
        with self._lock:
            return {
                k[len(prefix):]: self._substitute(v)
                for k, v in self._props.items() if k.startswith(prefix)
            }

    def size(self) -> int:
        with self._lock:
            return len(self._props)

    def __contains__(self, key: str) -> bool:
        return self.get_raw(key) is not None

    def __iter__(self) -> Iterator[Tuple[str, str]]:
        with self._lock:
            items = list(self._props.items())
        return iter(items)

    def to_dict(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._props)

    def copy(self) -> "Configuration":
        return Configuration(other=self)

    def write_json(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)

    # ------------------------------------------------------ reconfiguration

    def register_reconfigure_listener(
            self, cb: Callable[[str, Optional[str], Optional[str]], None]) -> None:
        """Live-reconfiguration hook (ref: conf/ReconfigurableBase.java):
        cb(key, old_value, new_value) fires on every set()."""
        with self._lock:
            self._reconf_listeners.append(cb)

    def __deepcopy__(self, memo):
        return Configuration(other=self)

    def __repr__(self) -> str:
        return f"Configuration({self.size()} props)"
