"""Shared conf keys and defaults — single-sourced (ref: DFSConfigKeys).

The reference centralises every key + default in per-subsystem
``*ConfigKeys`` classes precisely so two readers can never disagree
about a default. This module is the same move for the keys this tree
reads from MORE than one file: each constant pair here is the one
truth, and tpulint's ``conf/default-drift`` checker keeps it that way
(two sites reading one key with different literal defaults fail tier-1
on the empty baseline).

Keys read from exactly one site stay literal at that site — hoisting
them all here would just move 300 lines without adding a guarantee;
the generated registry (``hadoop_tpu/conf/registry.py``) already
records them.

``shipped_deprecations`` is the tree's DeprecationDelta table — old
spellings that tpulint's ``conf/typo-cluster`` checker caught reading
as two distinct keys (``store-dir``/``store.dir``,
``data.dirs``/``data.dir``) keep working for setters while every
reader sees the unified spelling.
"""

from hadoop_tpu.conf.configuration import ConfigRegistry, DeprecationDelta

# fs: the default filesystem URI. Empty-string / "/" spellings drifted
# across the CLIs; "file:///" is the canonical no-cluster default.
FS_DEFAULT_FS = "fs.defaultFS"
FS_DEFAULT_FS_DEFAULT = "file:///"

# fs: trash retention. 0 disables trash (ref: fs.trash.interval,
# core-default.xml) — commands that need a checkpoint period when trash
# is off (expunge) fall back explicitly rather than via a bigger default.
FS_TRASH_INTERVAL = "fs.trash.interval"
FS_TRASH_INTERVAL_DEFAULT = 0.0

# dfs: NameNode RPC endpoint(s), comma list for HA pairs.
DFS_NAMENODE_RPC_ADDRESS = "dfs.namenode.rpc-address"
DFS_NAMENODE_RPC_ADDRESS_DEFAULT = "127.0.0.1:8020"

# dfs: hedged reads are enabled by a NONZERO pool size (ref:
# dfs.client.hedged.read.threadpool.size, default 0 = off). The pool
# builder clamps to >=2 workers when hedging is live.
DFS_CLIENT_HEDGED_READ_POOL_SIZE = "dfs.client.hedged.read.threadpool.size"
DFS_CLIENT_HEDGED_READ_POOL_SIZE_DEFAULT = 0

# dfs: DataNode volume roots, comma list (ref: dfs.datanode.data.dir
# backing FsVolumeList). First entry is the primary/metadata volume;
# more than one entry makes the node multi-volume.
DFS_DATANODE_DATA_DIR = "dfs.datanode.data.dir"
DFS_DATANODE_DATA_DIR_DEFAULT = "/tmp/htpu-data"

# ipc: idle-connection close. The CLIENT closes a call-free connection
# after 10s (ref: ipc.client.connection.maxidletime, client reader);
# the SERVER's reaper keeps sockets longer so short-lived idle clients
# reconnect cheaply. These were one key read with two defaults — now
# two keys, each with one truth.
IPC_CLIENT_CONNECTION_MAXIDLETIME = "ipc.client.connection.maxidletime"
IPC_CLIENT_CONNECTION_MAXIDLETIME_DEFAULT = 10.0
IPC_SERVER_CONNECTION_MAXIDLETIME = "ipc.server.connection.maxidletime"
IPC_SERVER_CONNECTION_MAXIDLETIME_DEFAULT = 120.0

# yarn: timeline store root — one spelling for the NM collectors and
# the RM publisher (the "store-dir" twin is deprecated below).
YARN_TIMELINE_STORE_DIR = "yarn.timeline-service.store.dir"


def shipped_deprecations():
    """Fresh DeprecationDelta instances for the tree's renamed keys
    (fresh so warn-once state resets with the registry)."""
    return [
        DeprecationDelta("yarn.timeline-service.store-dir",
                         [YARN_TIMELINE_STORE_DIR]),
        DeprecationDelta("dfs.datanode.data.dirs",
                         [DFS_DATANODE_DATA_DIR]),
    ]


ConfigRegistry.add_deprecations(shipped_deprecations())
