"""Conf-key registry — GENERATED, do not edit by hand.

Regenerate:  ``hadoop-tpu lint --write-conf-registry``
Verify:      ``hadoop-tpu lint --check-conf-registry``  (tier-1 gate)

Extracted by ``hadoop_tpu/analysis/confscan.py`` from every statically
resolvable ``conf.get*`` call site in the tree. ``KEYS`` maps each
concrete key to its typed-getter type, the defaults read sites pass,
its namespace, whether the hand-written README documents it (the
generated appendix does not count), and the files that read it.
``PATTERNS`` holds dynamic key families (per-scheme / per-op / per-queue
keys) as fnmatch globs. ``LEVERS`` (hand-maintained in
``hadoop_tpu/conf/levers.py``, re-exported here) carries the
tunable-lever annotations — type, range hints, acceptance guard — that
the ROADMAP-4 autotuner consumes.
"""

from hadoop_tpu.conf.levers import LEVERS  # noqa: F401  (re-export)

ABSENT = "<absent>"    # a read site passes no default
DYNAMIC = "<dynamic>"  # default computed at runtime, not a literal


KEYS = {
    "conf.strict.keys": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'conf',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/conf/configuration.py',
        ),
    },
    "datajoin.tag": {
        "type": 'str',
        "defaults": ("'src'",),
        "namespace": 'datajoin',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/datajoin.py',
        ),
    },
    "dfs.block.access.token.enable": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'dfs',
        "documented": False, "sites": 3,
        "files": (
            'hadoop_tpu/dfs/balancer.py',
            'hadoop_tpu/dfs/datanode/datanode.py',
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
        ),
    },
    "dfs.blockreport.interval": {
        "type": 'time',
        "defaults": ('21600.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.blocksize": {
        "type": 'size',
        "defaults": ('134217728',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
        ),
    },
    "dfs.bytes-per-checksum": {
        "type": 'size',
        "defaults": ('512',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/client/dfsclient.py',
        ),
    },
    "dfs.client-write-packet-size": {
        "type": 'size',
        "defaults": ('1048576',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/client/dfsclient.py',
        ),
    },
    "dfs.client.hedged.read.threadpool.size": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'dfs',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/dfs/client/dfsclient.py',
            'hadoop_tpu/dfs/client/streams.py',
        ),
    },
    "dfs.client.hedged.read.threshold": {
        "type": 'time',
        "defaults": ('0.5',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/client/streams.py',
        ),
    },
    "dfs.client.observer.reads.enabled": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/client/dfsclient.py',
        ),
    },
    "dfs.client.read.shortcircuit": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/client/streams.py',
        ),
    },
    "dfs.client.write.max-packets-in-flight": {
        "type": 'int',
        "defaults": ('64',),
        "namespace": 'dfs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/client/dfsclient.py',
        ),
    },
    "dfs.client.write.socket.buffer": {
        "type": 'size',
        "defaults": ('0',),
        "namespace": 'dfs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/client/dfsclient.py',
        ),
    },
    "dfs.cluster.administrators": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.data.transfer.protection": {
        "type": 'str',
        "defaults": ("'privacy'",),
        "namespace": 'dfs',
        "documented": False, "sites": 3,
        "files": (
            'hadoop_tpu/dfs/balancer.py',
            'hadoop_tpu/dfs/client/dfsclient.py',
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.capacity": {
        "type": 'size',
        "defaults": ('0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.data.dir": {
        "type": 'list',
        "defaults": ('<dynamic>',),
        "namespace": 'dfs',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.directoryscan.interval": {
        "type": 'time',
        "defaults": ('21600.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.drop.cache.behind.writes": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.hostname": {
        "type": 'str',
        "defaults": ("'127.0.0.1'",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.http-port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.http.enabled": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.max.locked.memory": {
        "type": 'size',
        "defaults": ('67108864',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.scan.period": {
        "type": 'time',
        "defaults": ('10800.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.storage.type": {
        "type": 'str',
        "defaults": ("'DISK'",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.synconclose": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.volume-choosing-policy": {
        "type": 'str',
        "defaults": ("'available-space'",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.datanode.volumes": {
        "type": 'int',
        "defaults": ('1',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.domain.socket.path": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'dfs',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/dfs/client/streams.py',
            'hadoop_tpu/dfs/datanode/datanode.py',
        ),
    },
    "dfs.encrypt.data.transfer": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'dfs',
        "documented": False, "sites": 4,
        "files": (
            'hadoop_tpu/dfs/balancer.py',
            'hadoop_tpu/dfs/client/dfsclient.py',
            'hadoop_tpu/dfs/datanode/datanode.py',
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
        ),
    },
    "dfs.encryption.key.provider.uri": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'dfs',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/dfs/client/filesystem.py',
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
        ),
    },
    "dfs.federation.default.nameservice": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/router/router.py',
        ),
    },
    "dfs.federation.router.heartbeat.interval": {
        "type": 'time',
        "defaults": ('2.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/router/router.py',
        ),
    },
    "dfs.federation.router.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/router/router.py',
        ),
    },
    "dfs.federation.router.quota-cache.update.interval": {
        "type": 'time',
        "defaults": ('60.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/router/router.py',
        ),
    },
    "dfs.federation.router.store.dir": {
        "type": 'str',
        "defaults": ("'/tmp/htpu-router'",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/router/router.py',
        ),
    },
    "dfs.ha.automatic-failover.enabled": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.ha.health-check.interval": {
        "type": 'time',
        "defaults": ('0.5',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.ha.initial-state": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.ha.lease-duration": {
        "type": 'time',
        "defaults": ('4.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.ha.namenode.id": {
        "type": 'str',
        "defaults": ("'nn1'",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.ha.tail-edits.period": {
        "type": 'time',
        "defaults": ('0.5',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.heartbeat.interval": {
        "type": 'time',
        "defaults": ('3.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/dfs/datanode/datanode.py',
            'hadoop_tpu/dfs/namenode/blockmanager.py',
        ),
    },
    "dfs.journalnode.edits.dir": {
        "type": 'str',
        "defaults": ("'/tmp/htpu-journal'",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/qjournal.py',
        ),
    },
    "dfs.journalnode.handler.count": {
        "type": 'int',
        "defaults": ('4',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/qjournal.py',
        ),
    },
    "dfs.journalnode.rpc-port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/qjournal.py',
        ),
    },
    "dfs.lease.hard-limit": {
        "type": 'time',
        "defaults": ('1200.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
        ),
    },
    "dfs.lease.soft-limit": {
        "type": 'time',
        "defaults": ('60.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/dfs/client/dfsclient.py',
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
        ),
    },
    "dfs.namenode.checkpoint.period": {
        "type": 'time',
        "defaults": ('3600.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.namenode.checkpoint.txns": {
        "type": 'int',
        "defaults": ('1000000',),
        "namespace": 'dfs',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.namenode.handler.count": {
        "type": 'int',
        "defaults": ('8',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.namenode.heartbeat.recheck-interval": {
        "type": 'time',
        "defaults": ('10.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/blockmanager.py',
        ),
    },
    "dfs.namenode.http-port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.namenode.http.enabled": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.namenode.name.dir": {
        "type": 'str',
        "defaults": ("'/tmp/htpu-name'",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.namenode.reconstruction.pending.timeout": {
        "type": 'time',
        "defaults": ('30.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/blockmanager.py',
        ),
    },
    "dfs.namenode.redundancy.interval": {
        "type": 'time',
        "defaults": ('3.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.namenode.replication.min": {
        "type": 'int',
        "defaults": ('1',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/blockmanager.py',
        ),
    },
    "dfs.namenode.rpc-address": {
        "type": 'str',
        "defaults": ("'127.0.0.1:8020'",),
        "namespace": 'dfs',
        "documented": False, "sites": 5,
        "files": (
            'hadoop_tpu/cli/main.py',
            'hadoop_tpu/dfs/client/filesystem.py',
            'hadoop_tpu/dfs/datanode/datanode.py',
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "dfs.namenode.rpc-bind-host": {
        "type": 'str',
        "defaults": ("'127.0.0.1'",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.namenode.rpc-port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.namenode.safemode.extension": {
        "type": 'time',
        "defaults": ('0.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/blockmanager.py',
        ),
    },
    "dfs.namenode.safemode.threshold-pct": {
        "type": 'float',
        "defaults": ('0.999',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/blockmanager.py',
        ),
    },
    "dfs.namenode.scheduler.impl": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.namenode.shared.edits.dir": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/namenode.py',
        ),
    },
    "dfs.namenode.write-lock-reporting-threshold": {
        "type": 'time',
        "defaults": ('1.0',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
        ),
    },
    "dfs.permissions.enabled": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
        ),
    },
    "dfs.permissions.superusergroup": {
        "type": 'str',
        "defaults": ("'supergroup'",),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
        ),
    },
    "dfs.replication": {
        "type": 'int',
        "defaults": ('3',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
        ),
    },
    "dfs.replication.max": {
        "type": 'int',
        "defaults": ('512',),
        "namespace": 'dfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/namenode/blockmanager.py',
        ),
    },
    "distcp.update": {
        "type": 'str',
        "defaults": ("'true'",),
        "namespace": 'distcp',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/distcp.py',
        ),
    },
    "elastic.cooldown.polls": {
        "type": 'int',
        "defaults": ('3',),
        "namespace": 'elastic',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/elastic/__init__.py',
        ),
    },
    "elastic.dead.windows": {
        "type": 'int',
        "defaults": ('2',),
        "namespace": 'elastic',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/elastic/__init__.py',
        ),
    },
    "elastic.demote.windows": {
        "type": 'int',
        "defaults": ('2',),
        "namespace": 'elastic',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/elastic/__init__.py',
        ),
    },
    "elastic.enabled": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'elastic',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/elastic/__init__.py',
        ),
    },
    "elastic.evict.windows": {
        "type": 'int',
        "defaults": ('4',),
        "namespace": 'elastic',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/elastic/__init__.py',
        ),
    },
    "elastic.min-dp": {
        "type": 'int',
        "defaults": ('1',),
        "namespace": 'elastic',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/elastic/__init__.py',
        ),
    },
    "elastic.poll.steps": {
        "type": 'int',
        "defaults": ('20',),
        "namespace": 'elastic',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/elastic/__init__.py',
        ),
    },
    "fs.defaultFS": {
        "type": 'str',
        "defaults": ("'file:///'",),
        "namespace": 'fs',
        "documented": False, "sites": 9,
        "files": (
            'hadoop_tpu/cli/dfsadmin.py',
            'hadoop_tpu/cli/main.py',
            'hadoop_tpu/cli/shell.py',
        ),
    },
    "fs.trash.interval": {
        "type": 'time',
        "defaults": ('0.0',),
        "namespace": 'fs',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/cli/shell.py',
        ),
    },
    "gridmix.load.cpu-ms": {
        "type": 'str',
        "defaults": ("'0'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "gridmix.load.heap-mb": {
        "type": 'str',
        "defaults": ("'0'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "gridmix.load.map.input-records": {
        "type": 'str',
        "defaults": ("'100'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "gridmix.load.map.output-bytes": {
        "type": 'str',
        "defaults": ("'10000'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "gridmix.load.map.output-records": {
        "type": 'str',
        "defaults": ("'100'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "gridmix.load.maps": {
        "type": 'str',
        "defaults": ("'1'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "gridmix.load.record-bytes": {
        "type": 'str',
        "defaults": ("'100'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "gridmix.load.reduce.cpu-ms": {
        "type": 'str',
        "defaults": ("'0'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "gridmix.load.reduce.input-records": {
        "type": 'str',
        "defaults": ("'10000'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "gridmix.load.reduce.ratio": {
        "type": 'str',
        "defaults": ("'1'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "gridmix.sleep.maps": {
        "type": 'str',
        "defaults": ("'1'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "gridmix.sleep.ms": {
        "type": 'str',
        "defaults": ("'100'",),
        "namespace": 'gridmix',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/gridmix.py',
        ),
    },
    "hadoop.rpc.protection": {
        "type": 'str',
        "defaults": ("'authentication'",),
        "namespace": 'hadoop',
        "documented": False, "sites": 3,
        "files": (
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
            'hadoop_tpu/ipc/client.py',
            'hadoop_tpu/ipc/server.py',
        ),
    },
    "hadoop.security.authentication": {
        "type": 'str',
        "defaults": ("'simple'",),
        "namespace": 'hadoop',
        "documented": False, "sites": 5,
        "files": (
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
            'hadoop_tpu/dfs/namenode/namenode.py',
            'hadoop_tpu/dfs/router/router.py',
            'hadoop_tpu/ipc/client.py',
            'hadoop_tpu/ipc/server.py',
        ),
    },
    "hadoop.security.client.keytab": {
        "type": 'str',
        "defaults": ('None',),
        "namespace": 'hadoop',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/client.py',
        ),
    },
    "hadoop.security.group.mapping.static.mapping": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'hadoop',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/security/groups.py',
        ),
    },
    "hadoop.security.server.keytab": {
        "type": 'str',
        "defaults": ('None',),
        "namespace": 'hadoop',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/server.py',
        ),
    },
    "httpfs.authentication.signature.secret": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'httpfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/httpfs.py',
        ),
    },
    "httpfs.authentication.simple.anonymous.allowed": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'httpfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/httpfs.py',
        ),
    },
    "httpfs.http.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'httpfs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/dfs/httpfs.py',
        ),
    },
    "ipc.client.connect.timeout": {
        "type": 'time',
        "defaults": ('20.0',),
        "namespace": 'ipc',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/client.py',
        ),
    },
    "ipc.client.connection.maxidletime": {
        "type": 'time',
        "defaults": ('10.0',),
        "namespace": 'ipc',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/client.py',
        ),
    },
    "ipc.client.read.timeout": {
        "type": 'time',
        "defaults": ('120.0',),
        "namespace": 'ipc',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/client.py',
        ),
    },
    "ipc.client.rpc-timeout": {
        "type": 'time',
        "defaults": ('60.0',),
        "namespace": 'ipc',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/client.py',
        ),
    },
    "ipc.decay-scheduler.decay-factor": {
        "type": 'float',
        "defaults": ('0.5',),
        "namespace": 'ipc',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/callqueue.py',
        ),
    },
    "ipc.decay-scheduler.period": {
        "type": 'time',
        "defaults": ('5.0',),
        "namespace": 'ipc',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/callqueue.py',
        ),
    },
    "ipc.decay-scheduler.thresholds": {
        "type": 'list',
        "defaults": ('<absent>',),
        "namespace": 'ipc',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/callqueue.py',
        ),
    },
    "ipc.ping.interval": {
        "type": 'time',
        "defaults": ('10.0',),
        "namespace": 'ipc',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/client.py',
        ),
    },
    "ipc.server.connection.maxidletime": {
        "type": 'time',
        "defaults": ('120.0',),
        "namespace": 'ipc',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/server.py',
        ),
    },
    "ipc.server.reuseport": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'ipc',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/server.py',
        ),
    },
    "kms.acl.CREATE": {
        "type": 'str',
        "defaults": ("'*'",),
        "namespace": 'kms',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/crypto/kms.py',
        ),
    },
    "kms.acl.DECRYPT_EEK": {
        "type": 'str',
        "defaults": ("'*'",),
        "namespace": 'kms',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/crypto/kms.py',
        ),
    },
    "kms.acl.DELETE": {
        "type": 'str',
        "defaults": ("'*'",),
        "namespace": 'kms',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/crypto/kms.py',
        ),
    },
    "kms.acl.GENERATE_EEK": {
        "type": 'str',
        "defaults": ("'*'",),
        "namespace": 'kms',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/crypto/kms.py',
        ),
    },
    "kms.acl.GET": {
        "type": 'str',
        "defaults": ("'*'",),
        "namespace": 'kms',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/crypto/kms.py',
        ),
    },
    "kms.acl.GET_KEYS": {
        "type": 'str',
        "defaults": ("'*'",),
        "namespace": 'kms',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/crypto/kms.py',
        ),
    },
    "kms.acl.ROLLOVER": {
        "type": 'str',
        "defaults": ("'*'",),
        "namespace": 'kms',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/crypto/kms.py',
        ),
    },
    "kms.http.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'kms',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/crypto/kms.py',
        ),
    },
    "kms.key.provider.path": {
        "type": 'str',
        "defaults": ("'/tmp/htpu-kms/keys.json'",),
        "namespace": 'kms',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/crypto/kms.py',
        ),
    },
    "mapreduce.input.fixedlength.key.length": {
        "type": 'str',
        "defaults": ('10',),
        "namespace": 'mapreduce',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/mapreduce/api.py',
        ),
    },
    "mapreduce.input.fixedlength.record.length": {
        "type": 'str',
        "defaults": ('100',),
        "namespace": 'mapreduce',
        "documented": False, "sites": 3,
        "files": (
            'hadoop_tpu/mapreduce/api.py',
        ),
    },
    "mapreduce.input.split.size": {
        "type": 'str',
        "defaults": ('33554432',),
        "namespace": 'mapreduce',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/mapreduce/api.py',
        ),
    },
    "mapreduce.job.queuename": {
        "type": 'str',
        "defaults": ("'default'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/job.py',
        ),
    },
    "mapreduce.job.reduce.slowstart.completedmaps": {
        "type": 'str',
        "defaults": ("'0.05'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/appmaster.py',
        ),
    },
    "mapreduce.job.ubertask.enable": {
        "type": 'str',
        "defaults": ("'false'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/appmaster.py',
        ),
    },
    "mapreduce.job.ubertask.maxmaps": {
        "type": 'str',
        "defaults": ("'9'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/appmaster.py',
        ),
    },
    "mapreduce.job.ubertask.maxreduces": {
        "type": 'str',
        "defaults": ("'1'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/appmaster.py',
        ),
    },
    "mapreduce.jobhistory.done-dir": {
        "type": 'str',
        "defaults": ("'/mr-history/done'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/historyserver.py',
        ),
    },
    "mapreduce.jobhistory.webapp.bind-host": {
        "type": 'str',
        "defaults": ("'127.0.0.1'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/historyserver.py',
        ),
    },
    "mapreduce.jobhistory.webapp.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/historyserver.py',
        ),
    },
    "mapreduce.map.cpu.vcores": {
        "type": 'str',
        "defaults": ("'1'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/appmaster.py',
        ),
    },
    "mapreduce.map.maxattempts": {
        "type": 'str',
        "defaults": ("'4'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/appmaster.py',
        ),
    },
    "mapreduce.map.memory.mb": {
        "type": 'str',
        "defaults": ("'128'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/appmaster.py',
        ),
    },
    "mapreduce.map.output.compress": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/mapreduce/job.py',
            'hadoop_tpu/mapreduce/task_runner.py',
        ),
    },
    "mapreduce.map.output.compress.codec": {
        "type": 'str',
        "defaults": ('<absent>',),
        "namespace": 'mapreduce',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/mapreduce/job.py',
            'hadoop_tpu/mapreduce/task_runner.py',
        ),
    },
    "mapreduce.map.speculative": {
        "type": 'str',
        "defaults": ("'false'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/appmaster.py',
        ),
    },
    "mapreduce.output.replication": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/api.py',
        ),
    },
    "mapreduce.reduce.cpu.vcores": {
        "type": 'str',
        "defaults": ("'1'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/appmaster.py',
        ),
    },
    "mapreduce.reduce.memory.mb": {
        "type": 'str',
        "defaults": ("'128'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/appmaster.py',
        ),
    },
    "mapreduce.reduce.shuffle.memory.limit": {
        "type": 'str',
        "defaults": ('<dynamic>',),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/task_runner.py',
        ),
    },
    "mapreduce.reduce.shuffle.parallelcopies": {
        "type": 'str',
        "defaults": ("'4'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/task_runner.py',
        ),
    },
    "mapreduce.reduce.shuffle.timeout": {
        "type": 'str',
        "defaults": ("'600'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/task_runner.py',
        ),
    },
    "mapreduce.task.io.sort.mb": {
        "type": 'str',
        "defaults": ("'64'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/task_runner.py',
        ),
    },
    "mapreduce.task.timeout": {
        "type": 'str',
        "defaults": ("'120'",),
        "namespace": 'mapreduce',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/appmaster.py',
        ),
    },
    "metrics.prom.exemplars": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'metrics',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/http/server.py',
        ),
    },
    "namenode.audit.enable": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'namenode',
        "documented": True, "sites": 3,
        "files": (
            'hadoop_tpu/dfs/namenode/audit.py',
            'hadoop_tpu/dfs/namenode/fsnamesystem.py',
        ),
    },
    "net.topology.script.file.name": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'net',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/net/topology.py',
        ),
    },
    "net.topology.table": {
        "type": 'list',
        "defaults": ('()',),
        "namespace": 'net',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/net/topology.py',
        ),
    },
    "obs.comm.timing": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/comm.py',
        ),
    },
    "obs.doctor.endpoints": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.interval": {
        "type": 'time',
        "defaults": ('5.0',),
        "namespace": 'obs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.max-traces": {
        "type": 'int',
        "defaults": ('256',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/assemble.py',
        ),
    },
    "obs.doctor.namenode.http": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'obs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'obs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.push.namenode": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'obs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.registry": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'obs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.scrape.timeout": {
        "type": 'time',
        "defaults": ('2.0',),
        "namespace": 'obs',
        "documented": True, "sites": 2,
        "files": (
            'hadoop_tpu/obs/assemble.py',
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.service": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'obs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.slow.floor.ms": {
        "type": 'float',
        "defaults": ('1.0',),
        "namespace": 'obs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.slow.history": {
        "type": 'int',
        "defaults": ('5',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.slow.mad-k": {
        "type": 'float',
        "defaults": ('3.0',),
        "namespace": 'obs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.slow.min-peers": {
        "type": 'int',
        "defaults": ('3',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.slow.min-windows": {
        "type": 'int',
        "defaults": ('3',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.slow.ratio": {
        "type": 'float',
        "defaults": ('1.5',),
        "namespace": 'obs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.slow.ttl": {
        "type": 'time',
        "defaults": ('<dynamic>',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.doctor.trainer.service": {
        "type": 'str',
        "defaults": ("'/trainer-jobs'",),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/doctor.py',
        ),
    },
    "obs.slo.burn.fast": {
        "type": 'float',
        "defaults": ('14.0',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.burn.history": {
        "type": 'int',
        "defaults": ('5',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.burn.min-windows": {
        "type": 'int',
        "defaults": ('2',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.burn.slow": {
        "type": 'float',
        "defaults": ('2.0',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.class.map": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p0.availability": {
        "type": 'float',
        "defaults": ('0.99',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p0.token.p99.ms": {
        "type": 'float',
        "defaults": ('500.0',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p0.ttft.p99.ms": {
        "type": 'float',
        "defaults": ('2000.0',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p1.availability": {
        "type": 'float',
        "defaults": ('0.99',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p1.token.p99.ms": {
        "type": 'float',
        "defaults": ('500.0',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p1.ttft.p99.ms": {
        "type": 'float',
        "defaults": ('2000.0',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p2.availability": {
        "type": 'float',
        "defaults": ('0.99',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p2.token.p99.ms": {
        "type": 'float',
        "defaults": ('500.0',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p2.ttft.p99.ms": {
        "type": 'float',
        "defaults": ('2000.0',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p3.availability": {
        "type": 'float',
        "defaults": ('0.99',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p3.token.p99.ms": {
        "type": 'float',
        "defaults": ('500.0',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.p3.ttft.p99.ms": {
        "type": 'float',
        "defaults": ('2000.0',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.window.fast": {
        "type": 'int',
        "defaults": ('3',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.slo.window.slow": {
        "type": 'int',
        "defaults": ('12',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/slo.py',
        ),
    },
    "obs.trainer.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/trainer.py',
        ),
    },
    "obs.trainer.registry": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/trainer.py',
        ),
    },
    "obs.trainer.service": {
        "type": 'str',
        "defaults": ("'/trainer-jobs'",),
        "namespace": 'obs',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/obs/trainer.py',
        ),
    },
    "parallel.lowp.chunk-matmul": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "parallel.lowp.codec": {
        "type": 'str',
        "defaults": ("'int8'",),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "parallel.lowp.guard.rel-tol": {
        "type": 'float',
        "defaults": ('0.25',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "parallel.lowp.guard.steps": {
        "type": 'int',
        "defaults": ('50',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "parallel.lowp.quant.buckets": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "parallel.lowp.quant.group": {
        "type": 'int',
        "defaults": ('1024',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "parallel.lowp.quant.tp": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "parallel.lowp.quant.zero1-gather": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "parallel.lowp.sync.guard.rel-tol": {
        "type": 'float',
        "defaults": ('2.0',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "parallel.lowp.sync.mode": {
        "type": 'str',
        "defaults": ("'skip'",),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "parallel.lowp.sync.schedule": {
        "type": 'str',
        "defaults": ("'full'",),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "parallel.overlap.bucket.mb": {
        "type": 'int',
        "defaults": ('4',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/overlap.py',
        ),
    },
    "parallel.overlap.enabled": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/overlap.py',
        ),
    },
    "parallel.overlap.tp.chunks": {
        "type": 'int',
        "defaults": ('4',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/overlap.py',
        ),
    },
    "parallel.overlap.zero1.reduce-scatter": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/overlap.py',
        ),
    },
    "parallel.parity": {
        "type": 'str',
        "defaults": ("'bitwise'",),
        "namespace": 'parallel',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/parallel/lowp/__init__.py',
        ),
    },
    "registry.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'registry',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/registry/registry.py',
        ),
    },
    "registry.sweep.interval": {
        "type": 'time',
        "defaults": ('1.0',),
        "namespace": 'registry',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/registry/registry.py',
        ),
    },
    "serving.autoscale.backlog.high": {
        "type": 'float',
        "defaults": ('512.0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.breach.polls": {
        "type": 'int',
        "defaults": ('2',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.cooldown": {
        "type": 'time',
        "defaults": ('30.0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.doctor": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.drain.timeout": {
        "type": 'time',
        "defaults": ('120.0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.horizon": {
        "type": 'time',
        "defaults": ('60.0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.idle.polls": {
        "type": 'int',
        "defaults": ('5',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.interval": {
        "type": 'time',
        "defaults": ('10.0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.lead.max": {
        "type": 'float',
        "defaults": ('0.3',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.max": {
        "type": 'int',
        "defaults": ('8',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.min": {
        "type": 'int',
        "defaults": ('1',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.prefill.max": {
        "type": 'int',
        "defaults": ('4',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.prefill.min": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.queue.high": {
        "type": 'float',
        "defaults": ('2.0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.scalein.ttft.frac": {
        "type": 'float',
        "defaults": ('0.5',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.scrape.timeout": {
        "type": 'time',
        "defaults": ('2.0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/signals.py',
        ),
    },
    "serving.autoscale.slo.burn": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.ttft.p99.slo": {
        "type": 'time',
        "defaults": ('2.0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.util.high": {
        "type": 'float',
        "defaults": ('0.85',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.autoscale.util.low": {
        "type": 'float',
        "defaults": ('0.3',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/autoscale/controller.py',
        ),
    },
    "serving.http.auth.anonymous.allowed": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'serving',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/serving/server.py',
        ),
    },
    "serving.http.auth.secret": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'serving',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/serving/server.py',
        ),
    },
    "serving.kv.block.size": {
        "type": 'int',
        "defaults": ('16',),
        "namespace": 'serving',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.kv.codec": {
        "type": 'str',
        "defaults": ("'raw'",),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.kv.dfs.dir": {
        "type": 'str',
        "defaults": ("'/kvcache'",),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.kv.dfs.enable": {
        "type": 'bool',
        "defaults": ('<dynamic>',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.kv.dfs.min-refs": {
        "type": 'int',
        "defaults": ('1',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.kv.drain.persist": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.kv.fetch.window": {
        "type": 'int',
        "defaults": ('4',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.kv.hbm.bytes": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.kv.host.bytes": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.kv.num.blocks": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'serving',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.loader.io.workers": {
        "type": 'int',
        "defaults": ('4',),
        "namespace": 'serving',
        "documented": True, "sites": 2,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.longctx.chips": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/longctx/plane.py',
        ),
    },
    "serving.longctx.decode.fetch.windows": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/longctx/plane.py',
        ),
    },
    "serving.longctx.decode.pipeline": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/longctx/plane.py',
        ),
    },
    "serving.longctx.decode.sampler": {
        "type": 'str',
        "defaults": ("'device'",),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/longctx/plane.py',
        ),
    },
    "serving.longctx.decode.tail.tokens": {
        "type": 'int',
        "defaults": ('256',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/longctx/plane.py',
        ),
    },
    "serving.longctx.decode.window.blocks": {
        "type": 'int',
        "defaults": ('4',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/longctx/plane.py',
        ),
    },
    "serving.longctx.enabled": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.longctx.max.tokens": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/longctx/plane.py',
        ),
    },
    "serving.longctx.min.tokens": {
        "type": 'int',
        "defaults": ('4096',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/longctx/plane.py',
        ),
    },
    "serving.longctx.sp.mode": {
        "type": 'str',
        "defaults": ("'ring'",),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/longctx/plane.py',
        ),
    },
    "serving.max.batch": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.max.context": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'serving',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.max.lanes": {
        "type": 'int',
        "defaults": ('16',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.max.new.tokens": {
        "type": 'int',
        "defaults": ('1024',),
        "namespace": 'serving',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/serving/server.py',
        ),
    },
    "serving.moe.a2a.codec": {
        "type": 'str',
        "defaults": ("'int8'",),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.moe.capacity.factor": {
        "type": 'float',
        "defaults": ('0.0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.moe.shards": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.parity": {
        "type": 'str',
        "defaults": ("'bitwise'",),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/weightplane.py',
        ),
    },
    "serving.prefill.chunk": {
        "type": 'int',
        "defaults": ('16',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.prefix_cache.enabled": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.qos.decay.factor": {
        "type": 'float',
        "defaults": ('0.5',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/qos.py',
        ),
    },
    "serving.qos.decay.period": {
        "type": 'time',
        "defaults": ('5.0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/qos.py',
        ),
    },
    "serving.qos.enabled": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.qos.levels": {
        "type": 'int',
        "defaults": ('4',),
        "namespace": 'serving',
        "documented": True, "sites": 2,
        "files": (
            'hadoop_tpu/serving/qos.py',
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.qos.queue.max": {
        "type": 'int',
        "defaults": ('256',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/qos.py',
        ),
    },
    "serving.qos.retry.after": {
        "type": 'time',
        "defaults": ('1.0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/qos.py',
        ),
    },
    "serving.qos.shed.queue.depth": {
        "type": 'int',
        "defaults": ('32',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/qos.py',
        ),
    },
    "serving.qos.thresholds": {
        "type": 'list',
        "defaults": ('<absent>',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/qos.py',
        ),
    },
    "serving.registry.record.ttl": {
        "type": 'time',
        "defaults": ('<dynamic>',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/registry/registry.py',
        ),
    },
    "serving.registry.ttl": {
        "type": 'time',
        "defaults": ('10.0',),
        "namespace": 'serving',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/registry/registry.py',
        ),
    },
    "serving.role": {
        "type": 'str',
        "defaults": ("'mixed'",),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.router.affinity.enabled": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/router.py',
        ),
    },
    "serving.router.affinity.max.imbalance": {
        "type": 'int',
        "defaults": ('4',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/router.py',
        ),
    },
    "serving.router.affinity.prefix.tokens": {
        "type": 'int',
        "defaults": ('64',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/router.py',
        ),
    },
    "serving.router.max.retries": {
        "type": 'int',
        "defaults": ('6',),
        "namespace": 'serving',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/serving/router.py',
        ),
    },
    "serving.router.prefill.min.tokens": {
        "type": 'int',
        "defaults": ('32',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/router.py',
        ),
    },
    "serving.router.prefill.timeout": {
        "type": 'time',
        "defaults": ('20.0',),
        "namespace": 'serving',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/serving/router.py',
        ),
    },
    "serving.speculate.k": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.speculate.ngram": {
        "type": 'int',
        "defaults": ('3',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/service.py',
        ),
    },
    "serving.weights.codec": {
        "type": 'str',
        "defaults": ("'int8'",),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/weightplane.py',
        ),
    },
    "serving.weights.embed": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/weightplane.py',
        ),
    },
    "serving.weights.group": {
        "type": 'int',
        "defaults": ('64',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/weightplane.py',
        ),
    },
    "serving.weights.guard.min-agree": {
        "type": 'float',
        "defaults": ('0.95',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/weightplane.py',
        ),
    },
    "serving.weights.guard.rel-tol": {
        "type": 'float',
        "defaults": ('0.25',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/weightplane.py',
        ),
    },
    "serving.weights.head": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'serving',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/serving/weightplane.py',
        ),
    },
    "sls.queues": {
        "type": 'list',
        "defaults": ("('default',)",),
        "namespace": 'sls',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/tools/sls.py',
        ),
    },
    "terasort.partition.cutpoints": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'terasort',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/examples/terasort.py',
        ),
    },
    "test.reduce.gate": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'test',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/testing/mr_helpers.py',
        ),
    },
    "tracing.collector.max-spans": {
        "type": 'int',
        "defaults": ('<dynamic>',),
        "namespace": 'tracing',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/tracing/collector.py',
        ),
    },
    "tracing.flight.max-traces": {
        "type": 'int',
        "defaults": ('<dynamic>',),
        "namespace": 'tracing',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/tracing/collector.py',
        ),
    },
    "tracing.slow.ckpt.ms": {
        "type": 'float',
        "defaults": ('30000.0',),
        "namespace": 'tracing',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/tracing/collector.py',
        ),
    },
    "tracing.slow.client.ms": {
        "type": 'float',
        "defaults": ('2000.0',),
        "namespace": 'tracing',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/tracing/collector.py',
        ),
    },
    "tracing.slow.rpc.ms": {
        "type": 'float',
        "defaults": ('300.0',),
        "namespace": 'tracing',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/tracing/collector.py',
        ),
    },
    "tracing.slow.serving.ms": {
        "type": 'float',
        "defaults": ('1000.0',),
        "namespace": 'tracing',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/tracing/collector.py',
        ),
    },
    "tracing.slow.step.ms": {
        "type": 'float',
        "defaults": ('1000.0',),
        "namespace": 'tracing',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/tracing/collector.py',
        ),
    },
    "tracing.slow.xceiver.ms": {
        "type": 'float',
        "defaults": ('500.0',),
        "namespace": 'tracing',
        "documented": True, "sites": 1,
        "files": (
            'hadoop_tpu/tracing/collector.py',
        ),
    },
    "yarn.am.liveness-monitor.expiry-interval": {
        "type": 'time',
        "defaults": ('60.0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.app.mapreduce.am.resource.mb": {
        "type": 'str',
        "defaults": ("'256'",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/mapreduce/job.py',
        ),
    },
    "yarn.federation.liveness-interval": {
        "type": 'time',
        "defaults": ('2.0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/federation.py',
        ),
    },
    "yarn.federation.policy": {
        "type": 'str',
        "defaults": ("'load'",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/federation.py',
        ),
    },
    "yarn.federation.router.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/federation.py',
        ),
    },
    "yarn.federation.state-store.dir": {
        "type": 'str',
        "defaults": ("'/tmp/htpu-yarn-router'",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/federation.py',
        ),
    },
    "yarn.nm.liveness-monitor.expiry-interval": {
        "type": 'time',
        "defaults": ('60.0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.node-labels.map": {
        "type": 'list',
        "defaults": ('()',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/scheduler.py',
        ),
    },
    "yarn.nodemanager.aux-services": {
        "type": 'list',
        "defaults": ('<absent>',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/nm.py',
        ),
    },
    "yarn.nodemanager.bind-host": {
        "type": 'str',
        "defaults": ("'127.0.0.1'",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/nm.py',
        ),
    },
    "yarn.nodemanager.cgroups.root": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/nm.py',
        ),
    },
    "yarn.nodemanager.container-executor.class": {
        "type": 'str',
        "defaults": ("''",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/nm.py',
        ),
    },
    "yarn.nodemanager.container.memory-limit-mb": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/nm.py',
        ),
    },
    "yarn.nodemanager.heartbeat.interval": {
        "type": 'time',
        "defaults": ('1.0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/nm.py',
        ),
    },
    "yarn.nodemanager.local-dirs": {
        "type": 'str',
        "defaults": ("'/tmp/htpu-nm'",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/nm.py',
        ),
    },
    "yarn.nodemanager.resource.cpu-vcores": {
        "type": 'int',
        "defaults": ('8',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/nm.py',
        ),
    },
    "yarn.nodemanager.resource.memory-mb": {
        "type": 'int',
        "defaults": ('8192',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/nm.py',
        ),
    },
    "yarn.nodemanager.resource.tpu-chips": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/nm.py',
        ),
    },
    "yarn.resourcemanager.address": {
        "type": 'str',
        "defaults": ("'127.0.0.1:8032'",),
        "namespace": 'yarn',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/cli/main.py',
        ),
    },
    "yarn.resourcemanager.bind-host": {
        "type": 'str',
        "defaults": ("'127.0.0.1'",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.resourcemanager.handler.count": {
        "type": 'int',
        "defaults": ('8',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.resourcemanager.http-port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.resourcemanager.http.enabled": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.resourcemanager.monitor.capacity.preemption.monitoring_interval": {
        "type": 'time',
        "defaults": ('3.0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.resourcemanager.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.resourcemanager.scheduler.class": {
        "type": 'str',
        "defaults": ("'capacity'",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/scheduler.py',
        ),
    },
    "yarn.resourcemanager.scheduler.monitor.enable": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.resourcemanager.store.dir": {
        "type": 'str',
        "defaults": ("'/tmp/htpu-rm-state'",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.resourcemanager.work-preserving-recovery.enabled": {
        "type": 'bool',
        "defaults": ('True',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.router.clientrm.interceptors": {
        "type": 'str',
        "defaults": ("'audit,federation'",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/federation.py',
        ),
    },
    "yarn.scheduler.capacity.root.queues": {
        "type": 'list',
        "defaults": ("('default',)",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/scheduler.py',
        ),
    },
    "yarn.scheduler.fair.queues": {
        "type": 'list',
        "defaults": ("('default',)",),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/scheduler.py',
        ),
    },
    "yarn.scheduler.minimum-allocation-mb": {
        "type": 'int',
        "defaults": ('128',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/scheduler.py',
        ),
    },
    "yarn.sharedcache.cleaner.period": {
        "type": 'time',
        "defaults": ('60.0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/sharedcache.py',
        ),
    },
    "yarn.sharedcache.cleaner.resource-ttl": {
        "type": 'time',
        "defaults": ('3600.0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/sharedcache.py',
        ),
    },
    "yarn.sharedcache.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/sharedcache.py',
        ),
    },
    "yarn.timeline-service.enabled": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/nm.py',
        ),
    },
    "yarn.timeline-service.reader.webapp.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/timeline.py',
        ),
    },
    "yarn.timeline-service.store.backend": {
        "type": 'str',
        "defaults": ("'auto'",),
        "namespace": 'yarn',
        "documented": False, "sites": 4,
        "files": (
            'hadoop_tpu/yarn/nm.py',
            'hadoop_tpu/yarn/rm.py',
            'hadoop_tpu/yarn/timeline.py',
        ),
    },
    "yarn.timeline-service.store.dir": {
        "type": 'str',
        "defaults": ('<dynamic>',),
        "namespace": 'yarn',
        "documented": False, "sites": 2,
        "files": (
            'hadoop_tpu/yarn/nm.py',
            'hadoop_tpu/yarn/rm.py',
        ),
    },
    "yarn.timeline-service.webapp.port": {
        "type": 'int',
        "defaults": ('0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/timeline.py',
        ),
    },
}

PATTERNS = {
    "*.backoff.enable": {
        "type": 'bool',
        "defaults": ('False',),
        "namespace": '*',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/callqueue.py',
        ),
    },
    "*.callqueue.impl": {
        "type": 'str',
        "defaults": ("'fifo'",),
        "namespace": '*',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/callqueue.py',
        ),
    },
    "*.scheduler.impl": {
        "type": 'str',
        "defaults": ("'decay'", "'default'",),
        "namespace": '*',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/callqueue.py',
        ),
    },
    "*.scheduler.priority.levels": {
        "type": 'int',
        "defaults": ('4',),
        "namespace": '*',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/ipc/callqueue.py',
        ),
    },
    "datajoin.tag.*": {
        "type": 'str',
        "defaults": ('<absent>',),
        "namespace": 'datajoin',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/tools/datajoin.py',
        ),
    },
    "fs.*.endpoint": {
        "type": 'str',
        "defaults": ('None',),
        "namespace": 'fs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/fs/objectstore.py',
        ),
    },
    "fs.*.impl": {
        "type": 'class',
        "defaults": ('<absent>',),
        "namespace": 'fs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/fs/filesystem.py',
        ),
    },
    "fs.*.multipart.size": {
        "type": 'size',
        "defaults": ('8388608',),
        "namespace": 'fs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/fs/objectstore.py',
        ),
    },
    "fs.*.paging.maximum": {
        "type": 'int',
        "defaults": ('1000',),
        "namespace": 'fs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/fs/objectstore.py',
        ),
    },
    "fs.*.readahead": {
        "type": 'size',
        "defaults": ('262144',),
        "namespace": 'fs',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/fs/objectstore.py',
        ),
    },
    "yarn.scheduler.capacity.root.*.accessible-node-labels": {
        "type": 'list',
        "defaults": ('()',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/scheduler.py',
        ),
    },
    "yarn.scheduler.capacity.root.*.capacity": {
        "type": 'float',
        "defaults": ('<dynamic>',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/scheduler.py',
        ),
    },
    "yarn.scheduler.capacity.root.*.maximum-capacity": {
        "type": 'float',
        "defaults": ('100.0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/scheduler.py',
        ),
    },
    "yarn.scheduler.fair.root.*.weight": {
        "type": 'float',
        "defaults": ('1.0',),
        "namespace": 'yarn',
        "documented": False, "sites": 1,
        "files": (
            'hadoop_tpu/yarn/scheduler.py',
        ),
    },
}
