from hadoop_tpu.crypto.streams import (CryptoInputStream,  # noqa: F401
                                       CryptoOutputStream)
from hadoop_tpu.crypto.keys import (KeyProvider,  # noqa: F401
                                    FileKeyProvider, KMSClientProvider)
