"""KeyProvider API + file-backed keystore + KMS client.

Parity with the reference's key-management layer (ref: hadoop-common
crypto/key/KeyProvider.java, JavaKeyStoreProvider.java,
KeyProviderCryptoExtension.java (EEK generate/decrypt),
kms/KMSClientProvider.java): named keys with rolled versions; EDEKs
(encrypted data-encryption-keys) are generated under a zone key and can
only be decrypted by the provider — the NameNode never sees plaintext
DEKs (the envelope-encryption contract encryption zones rely on).
"""

from __future__ import annotations

import base64
import json
import os
import threading
from typing import Dict, List, Optional

from hadoop_tpu.crypto.streams import _crypt


class KeyVersion:
    __slots__ = ("name", "version", "material")

    def __init__(self, name: str, version: str, material: bytes):
        self.name = name
        self.version = version
        self.material = material


class EncryptedKeyVersion:
    """An EDEK: DEK encrypted under a zone-key version.
    Ref: KeyProviderCryptoExtension.EncryptedKeyVersion."""

    __slots__ = ("key_name", "key_version", "iv", "edek")

    def __init__(self, key_name: str, key_version: str, iv: bytes,
                 edek: bytes):
        self.key_name = key_name
        self.key_version = key_version
        self.iv = iv
        self.edek = edek

    def to_wire(self) -> Dict:
        return {"kn": self.key_name, "kv": self.key_version,
                "iv": self.iv, "e": self.edek}

    @classmethod
    def from_wire(cls, d: Dict) -> "EncryptedKeyVersion":
        return cls(d["kn"], d["kv"], d["iv"], d["e"])


class KeyProvider:
    """Abstract provider. Ref: crypto/key/KeyProvider.java."""

    def create_key(self, name: str, bits: int = 128) -> KeyVersion:
        raise NotImplementedError

    def roll_key(self, name: str) -> KeyVersion:
        raise NotImplementedError

    def get_current_key(self, name: str) -> KeyVersion:
        raise NotImplementedError

    def get_key_version(self, name: str, version: str) -> KeyVersion:
        raise NotImplementedError

    def get_keys(self) -> List[str]:
        raise NotImplementedError

    def delete_key(self, name: str) -> None:
        raise NotImplementedError

    # -- crypto extension (envelope encryption) --

    def generate_encrypted_key(self, name: str) -> EncryptedKeyVersion:
        """Fresh random DEK, returned encrypted under the named key."""
        zone_key = self.get_current_key(name)
        dek = os.urandom(len(zone_key.material))
        iv = os.urandom(16)
        edek = _crypt(zone_key.material, iv, 0, dek)
        return EncryptedKeyVersion(name, zone_key.version, iv, edek)

    def decrypt_encrypted_key(self, ekv: EncryptedKeyVersion) -> bytes:
        zone_key = self.get_key_version(ekv.key_name, ekv.key_version)
        return _crypt(zone_key.material, ekv.iv, 0, ekv.edek)


class FileKeyProvider(KeyProvider):
    """JSON keystore on local disk (ref: JavaKeyStoreProvider — minus the
    JCEKS container; file permissions are the guard)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._keys: Dict[str, Dict] = {}
        self._load()

    def _load(self) -> None:
        if os.path.exists(self.path):
            with open(self.path) as f:
                raw = json.load(f)
            self._keys = {
                name: {"current": k["current"],
                       "versions": {v: base64.b64decode(m)
                                    for v, m in k["versions"].items()}}
                for name, k in raw.items()}

    def _save_locked(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        raw = {
            name: {"current": k["current"],
                   "versions": {v: base64.b64encode(m).decode()
                                for v, m in k["versions"].items()}}
            for name, k in self._keys.items()}
        tmp = self.path + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(raw, f)
        os.replace(tmp, self.path)

    def create_key(self, name: str, bits: int = 128) -> KeyVersion:
        with self._lock:
            if name in self._keys:
                raise KeyError(f"key {name} exists")
            material = os.urandom(bits // 8)
            self._keys[name] = {"current": f"{name}@0",
                                "versions": {f"{name}@0": material}}
            self._save_locked()
            return KeyVersion(name, f"{name}@0", material)

    def roll_key(self, name: str) -> KeyVersion:
        with self._lock:
            k = self._keys[name]
            n = len(k["versions"])
            version = f"{name}@{n}"
            material = os.urandom(len(next(iter(k["versions"].values()))))
            k["versions"][version] = material
            k["current"] = version
            self._save_locked()
            return KeyVersion(name, version, material)

    def get_current_key(self, name: str) -> KeyVersion:
        with self._lock:
            k = self._keys.get(name)
            if k is None:
                raise KeyError(f"no such key {name}")
            return KeyVersion(name, k["current"],
                              k["versions"][k["current"]])

    def get_key_version(self, name: str, version: str) -> KeyVersion:
        with self._lock:
            k = self._keys.get(name)
            if k is None or version not in k["versions"]:
                raise KeyError(f"no such key version {version}")
            return KeyVersion(name, version, k["versions"][version])

    def get_keys(self) -> List[str]:
        with self._lock:
            return sorted(self._keys)

    def delete_key(self, name: str) -> None:
        with self._lock:
            self._keys.pop(name, None)
            self._save_locked()


class KMSClientProvider(KeyProvider):
    """REST client for the KMS daemon (ref: kms/KMSClientProvider.java;
    server endpoints mirror hadoop-kms KMS.java)."""

    def __init__(self, base_url: str):
        self.base = base_url.rstrip("/")

    def _req(self, method: str, path: str, body: Optional[Dict] = None):
        import urllib.request
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(f"{self.base}{path}", data=data,
                                     method=method)
        if data:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=15) as r:
                payload = r.read()
                return json.loads(payload) if payload else {}
        except Exception as e:  # noqa: BLE001 — surface as KeyError/IOError
            import urllib.error
            if isinstance(e, urllib.error.HTTPError) and e.code == 404:
                raise KeyError(f"KMS: {path} not found") from e
            raise

    @staticmethod
    def _kv(d: Dict) -> KeyVersion:
        return KeyVersion(d["name"], d["versionName"],
                          base64.b64decode(d["material"]))

    def create_key(self, name: str, bits: int = 128) -> KeyVersion:
        return self._kv(self._req("POST", "/kms/v1/keys",
                                  {"name": name, "length": bits}))

    def roll_key(self, name: str) -> KeyVersion:
        return self._kv(self._req("POST", f"/kms/v1/key/{name}/_roll", {}))

    def get_current_key(self, name: str) -> KeyVersion:
        return self._kv(self._req("GET",
                                  f"/kms/v1/key/{name}/_currentversion"))

    def get_key_version(self, name: str, version: str) -> KeyVersion:
        return self._kv(self._req("GET", f"/kms/v1/keyversion/{version}"))

    def get_keys(self) -> List[str]:
        return self._req("GET", "/kms/v1/keys/names")

    def delete_key(self, name: str) -> None:
        self._req("DELETE", f"/kms/v1/key/{name}")

    def generate_encrypted_key(self, name: str) -> EncryptedKeyVersion:
        # the server routes on eek_op and nests the edek material
        # (kms.py _route; ref: KMS.java generateEncryptedKeys response)
        d = self._req("GET", f"/kms/v1/key/{name}/_eek?eek_op=generate")
        return EncryptedKeyVersion(
            d["name"], d["versionName"], base64.b64decode(d["iv"]),
            base64.b64decode(d["encryptedKeyVersion"]["material"]))

    def decrypt_encrypted_key(self, ekv: EncryptedKeyVersion) -> bytes:
        d = self._req("POST", f"/kms/v1/keyversion/{ekv.key_version}"
                              f"/_eek?eek_op=decrypt",
                      {"name": ekv.key_name,
                       "iv": base64.b64encode(ekv.iv).decode(),
                       "material": base64.b64encode(ekv.edek).decode()})
        return base64.b64decode(d["material"])


def make_provider(uri: str) -> KeyProvider:
    """kms://http@host:port → KMSClientProvider; file:///path or a bare
    path → FileKeyProvider (ref: KeyProviderFactory URI dispatch)."""
    if uri.startswith("kms://"):
        rest = uri[len("kms://"):]
        scheme, _, hostport = rest.partition("@")
        return KMSClientProvider(f"{scheme or 'http'}://{hostport}")
    if uri.startswith("file://"):
        uri = uri[len("file://"):]
    return FileKeyProvider(uri)
