"""KMS — the key management server + its client-side KeyProvider.

Parity with the reference KMS (ref: hadoop-common-project/hadoop-kms —
KMS.java's REST resource (/kms/v1/…), KMSClientProvider.java on the
client side, KMSACLs.java for per-op ACLs): a small REST face over any
``KeyProvider`` (the FileKeyProvider by default), with per-operation
user ACLs, serving key metadata and the EDEK generate/decrypt pair that
encryption-at-rest clients use; ``KMSKeyProvider`` makes a remote KMS
look like a local provider behind the same seam.

Endpoints (the reference's shapes, JSON):
  GET    /kms/v1/keys/names                  list keys
  POST   /kms/v1/keys                        {name, length} create
  GET    /kms/v1/key/<name>/_currentversion
  GET    /kms/v1/key/<name>/_eek?eek_op=generate
  POST   /kms/v1/keyversion/<ver>/_eek?eek_op=decrypt   {iv, material,name}
  DELETE /kms/v1/key/<name>
"""

from __future__ import annotations

import base64
import json
import logging
from typing import Dict, List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.crypto.keys import (EncryptedKeyVersion, FileKeyProvider,
                                    KeyProvider, KeyVersion)
from hadoop_tpu.http.server import HttpServer
from hadoop_tpu.service import AbstractService

log = logging.getLogger(__name__)


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


class KMSACLs:
    """Per-operation user allowlists (ref: KMSACLs.java; keys like
    ``kms.acl.CREATE = alice,bob`` — '*' or unset = everyone)."""

    OPS = ("CREATE", "DELETE", "ROLLOVER", "GET", "GET_KEYS",
           "GENERATE_EEK", "DECRYPT_EEK")

    def __init__(self, conf: Configuration):
        self._acl: Dict[str, Optional[set]] = {}
        for op in self.OPS:
            spec = conf.get(f"kms.acl.{op}", "*").strip()
            self._acl[op] = None if spec == "*" else {
                u.strip() for u in spec.split(",") if u.strip()}

    def check(self, op: str, user: str) -> None:
        allowed = self._acl.get(op)
        if allowed is not None and user not in allowed:
            raise PermissionError(f"user {user!r} lacks KMS ACL {op}")


class KMSServer(AbstractService):
    def __init__(self, conf: Configuration,
                 provider: Optional[KeyProvider] = None):
        super().__init__("KMSServer")
        self._provider_in = provider
        self.http: Optional[HttpServer] = None

    def service_init(self, conf: Configuration) -> None:
        self.provider = self._provider_in or FileKeyProvider(
            conf.get("kms.key.provider.path", "/tmp/htpu-kms/keys.json"))
        self.acls = KMSACLs(conf)
        self.http = HttpServer(
            conf, ("127.0.0.1", conf.get_int("kms.http.port", 0)),
            daemon_name="kms")
        self.http.add_handler("/kms/v1/", self._route)

    def service_start(self) -> None:
        self.http.start()
        log.info("KMS on :%d", self.http.port)

    def service_stop(self) -> None:
        if self.http:
            self.http.stop()

    @property
    def port(self) -> int:
        return self.http.port

    # -------------------------------------------------------------- routes

    def _route(self, query: Dict, body: bytes):
        path = query["__path__"][len("/kms/v1/"):].strip("/")
        method = query.get("__method__", "GET")
        user = query.get("user.name", "anonymous")
        parts = path.split("/")
        payload = json.loads(body.decode()) if body else {}

        if parts[0] == "keys" and len(parts) == 2 and parts[1] == "names":
            self.acls.check("GET_KEYS", user)
            return 200, self.provider.get_keys()
        if parts[0] == "keys" and method == "POST":
            self.acls.check("CREATE", user)
            kv = self.provider.create_key(payload["name"],
                                          payload.get("length", 128))
            return 201, self._kv_json(kv)
        if parts[0] == "key" and len(parts) >= 2:
            name = parts[1]
            if method == "DELETE":
                self.acls.check("DELETE", user)
                self.provider.delete_key(name)
                return 200, {"deleted": name}
            if len(parts) == 3 and parts[2] == "_currentversion":
                self.acls.check("GET", user)
                return 200, self._kv_json(self.provider.get_current_key(name))
            if len(parts) == 3 and parts[2] == "_eek":
                if query.get("eek_op") == "generate":
                    self.acls.check("GENERATE_EEK", user)
                    ekv = self.provider.generate_encrypted_key(name)
                    return 200, {
                        "versionName": ekv.key_version,
                        "iv": _b64(ekv.iv),
                        "encryptedKeyVersion": {
                            "material": _b64(ekv.edek)},
                        "name": ekv.key_name,
                    }
            if len(parts) == 3 and parts[2] == "_roll" and method == "POST":
                self.acls.check("ROLLOVER", user)
                return 200, self._kv_json(self.provider.roll_key(name))
        if parts[0] == "keyversion" and len(parts) == 3 and \
                parts[2] == "_eek" and query.get("eek_op") == "decrypt":
            self.acls.check("DECRYPT_EEK", user)
            ekv = EncryptedKeyVersion(
                payload["name"], parts[1], _unb64(payload["iv"]),
                _unb64(payload["material"]))
            material = self.provider.decrypt_encrypted_key(ekv)
            return 200, {"material": _b64(material)}
        raise FileNotFoundError(path)

    @staticmethod
    def _kv_json(kv: KeyVersion) -> Dict:
        return {"name": kv.name, "versionName": kv.version,
                "material": _b64(kv.material)}


class KMSKeyProvider(KeyProvider):
    """Client provider speaking to a KMSServer (ref:
    KMSClientProvider.java). Plugs into the same KeyProvider seam the
    crypto streams use."""

    def __init__(self, addr: str, user: str = "client"):
        import urllib.request
        self._base = f"http://{addr}/kms/v1"
        self._user = user
        self._rq = urllib.request

    def _call(self, method: str, path: str, body: Optional[Dict] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = self._rq.Request(
            f"{self._base}/{path}"
            f"{'&' if '?' in path else '?'}user.name={self._user}",
            data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with self._rq.urlopen(req) as resp:
                return json.loads(resp.read().decode())
        except Exception as e:
            import urllib.error
            if isinstance(e, urllib.error.HTTPError):
                detail = e.read().decode(errors="replace")
                if e.code == 403 or (e.code == 500 and
                                      "PermissionError" in detail):
                    # the server maps PermissionError → 403 (older
                    # servers used a generic 500)
                    raise PermissionError(detail) from e
                raise IOError(f"KMS {e.code}: {detail}") from e
            raise

    def create_key(self, name: str, bits: int = 128) -> KeyVersion:
        d = self._call("POST", "keys", {"name": name, "length": bits})
        return KeyVersion(d["name"], d["versionName"], _unb64(d["material"]))

    def get_current_key(self, name: str) -> KeyVersion:
        d = self._call("GET", f"key/{name}/_currentversion")
        return KeyVersion(d["name"], d["versionName"], _unb64(d["material"]))

    def roll_key(self, name: str) -> KeyVersion:
        d = self._call("POST", f"key/{name}/_roll", {})
        return KeyVersion(d["name"], d["versionName"], _unb64(d["material"]))

    def get_keys(self) -> List[str]:
        return self._call("GET", "keys/names")

    def delete_key(self, name: str) -> None:
        self._call("DELETE", f"key/{name}")

    def generate_encrypted_key(self, name: str) -> EncryptedKeyVersion:
        d = self._call("GET", f"key/{name}/_eek?eek_op=generate")
        return EncryptedKeyVersion(
            d["name"], d["versionName"], _unb64(d["iv"]),
            _unb64(d["encryptedKeyVersion"]["material"]))

    def decrypt_encrypted_key(self, ekv: EncryptedKeyVersion) -> bytes:
        d = self._call(
            "POST", f"keyversion/{ekv.key_version}/_eek?eek_op=decrypt",
            {"name": ekv.key_name, "iv": _b64(ekv.iv),
             "material": _b64(ekv.edek)})
        return _unb64(d["material"])
