"""AES-CTR crypto streams — transparent encryption at rest.

Parity with the reference (ref: hadoop-common
crypto/CryptoInputStream.java (874 LoC), CryptoOutputStream.java,
CTRCryptoCodec/OpensslAesCtrCryptoCodec): CTR mode gives seekable,
length-preserving encryption — the counter for byte offset N is
IV + N//16, so positioned reads decrypt without touching earlier bytes.
The cipher is OpenSSL-backed (via the `cryptography` package, the same
EVP machinery the reference reaches through JNI).
"""

from __future__ import annotations

from typing import Optional

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

AES_BLOCK = 16


def _counter_iv(iv: bytes, offset: int) -> bytes:
    """IV advanced by offset//16 blocks (ref: CTRCryptoCodec
    .calculateIV)."""
    ctr = int.from_bytes(iv, "big") + offset // AES_BLOCK
    return (ctr % (1 << 128)).to_bytes(16, "big")


def _crypt(key: bytes, iv: bytes, offset: int, data: bytes) -> bytes:
    """En/decrypt ``data`` positioned at stream ``offset`` (CTR is its
    own inverse). Handles intra-block alignment by prepending skip
    bytes."""
    pre = offset % AES_BLOCK
    cipher = Cipher(algorithms.AES(key),
                    modes.CTR(_counter_iv(iv, offset)))
    enc = cipher.encryptor()
    if pre:
        enc.update(b"\0" * pre)  # burn the partial leading block
    return enc.update(data)


class CryptoOutputStream:
    """Encrypting wrapper over any write/close stream."""

    def __init__(self, inner, key: bytes, iv: bytes):
        self.inner = inner
        self.key = key
        self.iv = iv
        self._pos = 0

    def write(self, data: bytes) -> int:
        out = _crypt(self.key, self.iv, self._pos, data)
        self.inner.write(out)
        self._pos += len(data)
        return len(data)

    def flush(self) -> None:
        if hasattr(self.inner, "flush"):
            self.inner.flush()

    def tell(self) -> int:
        return self._pos

    def close(self) -> None:
        self.inner.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *rest):
        if exc_type is None:
            self.close()
        return False


class CryptoInputStream:
    """Decrypting wrapper preserving seek/tell/pread semantics."""

    def __init__(self, inner, key: bytes, iv: bytes):
        self.inner = inner
        self.key = key
        self.iv = iv

    def read(self, n: int = -1) -> bytes:
        pos = self.inner.tell()
        data = self.inner.read(n)
        return _crypt(self.key, self.iv, pos, data)

    def pread(self, position: int, length: int) -> bytes:
        if hasattr(self.inner, "pread"):
            raw = self.inner.pread(position, length)
        else:
            saved = self.inner.tell()
            self.inner.seek(position)
            raw = self.inner.read(length)
            self.inner.seek(saved)
        return _crypt(self.key, self.iv, position, raw)

    def seek(self, pos: int) -> None:
        self.inner.seek(pos)

    def tell(self) -> int:
        return self.inner.tell()

    @property
    def length(self) -> Optional[int]:
        return getattr(self.inner, "length", None)

    def close(self) -> None:
        self.inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
