"""hadoop_tpu.dfs — the distributed filesystem.

Capability-equivalent rebuild of HDFS (ref: hadoop-hdfs-project): a metadata
master (``namenode``) holding the namespace in memory backed by a write-ahead
edit log + checkpoint images; block servers (``datanode``) storing replicated
blocks and streaming them over a packet protocol with per-chunk CRCs; and a
client (``client``) with pipelined writes and replica-failover reads.
"""
