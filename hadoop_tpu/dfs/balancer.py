"""Balancer + Mover: cluster rebalancing and storage-policy satisfaction.

Balancer parity (ref: hadoop-hdfs server/balancer/Balancer.java:177
(:753 run, :1006 main), Dispatcher.java): iterate until every node's
utilization is within ``threshold`` of the cluster mean — each round
pairs over- with under-utilized nodes and moves blocks directly between
DataNodes (the source pushes via OP_TRANSFER_BLOCK); the NameNode then
sees the extra replica and prunes the excess copy from the fullest node,
completing the move.

Mover parity (ref: server/mover/Mover.java): walk the namespace, and for
every file whose effective storage policy demands a media class its
replicas don't sit on, copy the replica onto a node of the wanted type
and drop the misplaced one.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.protocol import datatransfer as dt
from hadoop_tpu.dfs.protocol.records import (POLICY_TYPES, Block,
                                             DatanodeInfo)
from hadoop_tpu.ipc import Client, get_proxy

log = logging.getLogger(__name__)



def _transfer_security(conf: Configuration, nn):
    """Dial-side security for a standalone balancer/mover process (ref:
    the reference tools resolve SaslDataTransferClient from conf)."""
    if not conf.get_bool("dfs.encrypt.data.transfer", False):
        return dt.default_security()
    return dt.TransferSecurity(
        nn.get_data_encryption_key,
        qop=conf.get("dfs.data.transfer.protection", "privacy"))

def _block_token_minter(conf: Configuration, nn):
    """Balancer-side token minter (ref: balancer/KeyManager.java — the
    balancer fetches the NN's block keys once and mints its own COPY
    tokens, refreshing on rotation)."""
    if not conf.get_bool("dfs.block.access.token.enable", False):
        return None
    from hadoop_tpu.dfs.protocol.blocktoken import BlockTokenSecretManager
    mgr = BlockTokenSecretManager.for_verification()
    mgr.import_keys(nn.get_block_keys())
    return mgr


def _transfer(source: DatanodeInfo, block: Block,
              target: DatanodeInfo, security=None, tokens=None) -> None:
    """Command ``source`` to push one replica to ``target``."""
    req_tok = None
    if tokens is not None:
        from hadoop_tpu.dfs.protocol import blocktoken as bt
        req_tok = tokens.generate_token("balancer", block.block_id,
                                        (bt.MODE_COPY,))
    sock = dt.connect(source.xfer_addr(), timeout=10.0, security=security)
    try:
        dt.send_frame(sock, {"op": dt.OP_TRANSFER_BLOCK,
                             "b": block.to_wire(), "tok": req_tok,
                             "targets": [target.to_wire()]})
        resp = dt.recv_frame(sock)
        if not resp.get("ok"):
            raise IOError(resp.get("em", "transfer failed"))
    finally:
        sock.close()


class Balancer:
    """Ref: balancer/Balancer.java — returns when balanced or stuck."""

    def __init__(self, nn_addrs, conf: Optional[Configuration] = None,
                 threshold: float = 0.10, max_moves_per_round: int = 16):
        self.conf = conf or Configuration()
        self.threshold = threshold
        self.max_moves_per_round = max_moves_per_round
        self._client = Client(self.conf)
        if isinstance(nn_addrs, tuple):
            nn_addrs = [nn_addrs]
        self.nn = get_proxy("ClientProtocol", nn_addrs[0],
                            client=self._client)
        self.security = _transfer_security(self.conf, self.nn)
        self.tokens = _block_token_minter(self.conf, self.nn)

    def close(self) -> None:
        self._client.stop()

    def _report(self) -> List[DatanodeInfo]:
        return [DatanodeInfo.from_wire(d)
                for d in self.nn.get_datanode_report("live")]

    def run(self, max_rounds: int = 50,
            settle_s: float = 0.5) -> Dict[str, int]:
        """Iterate move rounds until balanced. Returns stats."""
        moved = 0
        rounds = 0
        for _ in range(max_rounds):
            rounds += 1
            nodes = self._report()
            plan = self._plan_round(nodes)
            if not plan:
                break
            ok = 0
            for source, block, target in plan:
                try:
                    _transfer(source, block, target,
                              security=self.security, tokens=self.tokens)
                    ok += 1
                    moved += 1
                except (OSError, IOError) as e:
                    log.warning("move of %s %s→%s failed: %s", block,
                                source, target, e)
            if ok == 0:
                break
            # fixed settle cadence (not a retry: lets IBRs land)
            time.sleep(settle_s)  # lint: disable=rpc/retry-no-backoff
        return {"rounds": rounds, "blocks_moved": moved}

    def _plan_round(self, nodes: List[DatanodeInfo]
                    ) -> List[Tuple[DatanodeInfo, Block, DatanodeInfo]]:
        """Pair over-/under-utilized nodes (ref: Balancer.init's
        over/above/below/underUtilized classification)."""
        if len(nodes) < 2:
            return []
        mean = sum(n.utilization() for n in nodes) / len(nodes)
        over = sorted((n for n in nodes
                       if n.utilization() > mean + self.threshold),
                      key=lambda n: -n.utilization())
        under = sorted((n for n in nodes
                        if n.utilization() < mean - self.threshold),
                       key=lambda n: n.utilization())
        if not over or not under:
            return []
        plan = []
        for src in over:
            blocks = [Block.from_wire(b)
                      for b in self.nn.get_blocks(src.uuid,
                                                  self.max_moves_per_round)]
            ui = 0
            for block in blocks:
                if len(plan) >= self.max_moves_per_round or not under:
                    break
                # Skip targets that already hold a replica; move only
                # within the source's storage type — cross-type migration
                # is the Mover's job, and a cross-type copy would be
                # pruned as policy-violating, re-planning forever (ref:
                # Dispatcher's same-StorageType matching).
                locs = {d["u"] for d in self.nn.get_block_datanodes(
                    block.to_wire())}
                candidates = [u for u in under if u.uuid not in locs
                              and u.storage_type == src.storage_type]
                if not candidates:
                    continue
                target = candidates[ui % len(candidates)]
                ui += 1
                plan.append((src, block, target))
        return plan


class Mover:
    """Ref: mover/Mover.java — migrate replicas onto the storage types
    their file's policy wants."""

    def __init__(self, nn_addrs, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()
        self._client = Client(self.conf)
        if isinstance(nn_addrs, tuple):
            nn_addrs = [nn_addrs]
        self.nn = get_proxy("ClientProtocol", nn_addrs[0],
                            client=self._client)
        self.security = _transfer_security(self.conf, self.nn)
        self.tokens = _block_token_minter(self.conf, self.nn)

    def close(self) -> None:
        self._client.stop()

    def run(self, root: str = "/", settle_s: float = 0.5) -> Dict[str, int]:
        moved = 0
        scanned = 0
        # One datanode report per pass — it is file-independent.
        live = [DatanodeInfo.from_wire(d)
                for d in self.nn.get_datanode_report("live")]
        stack = [root]
        while stack:
            path = stack.pop()
            for st in self.nn.listing(path):
                p = st["p"]
                if st["d"]:
                    stack.append(p)
                    continue
                scanned += 1
                moved += self._satisfy_file(p, live)
        if moved:
            time.sleep(settle_s)
        return {"files_scanned": scanned, "replicas_moved": moved}

    def _satisfy_file(self, path: str, live: List[DatanodeInfo]) -> int:
        policy = self.nn.get_storage_policy(path)
        wanted = POLICY_TYPES.get(policy, ["DISK"])
        info = self.nn.get_block_locations(path, 0, 1 << 62)
        right_type = [n for n in live if n.storage_type in wanted]
        if not right_type:
            return 0  # no node of the wanted class exists — nothing to do
        moves = 0
        for bw in info["blocks"]:
            if bw.get("ec"):
                continue  # striped groups are not moved (parity w/ Mover)
            block = Block.from_wire(bw["b"])
            locs = [DatanodeInfo.from_wire(d) for d in bw["locs"]]
            misplaced = [d for d in locs if d.storage_type not in wanted]
            placed_uuids = {d.uuid for d in locs}
            for bad in misplaced:
                target = next((t for t in right_type
                               if t.uuid not in placed_uuids), None)
                if target is None:
                    break
                try:
                    _transfer(bad, block, target, security=self.security,
                              tokens=self.tokens)
                    placed_uuids.add(target.uuid)
                    # Wait for the new replica to register, then retire the
                    # misplaced copy (invalidating first could momentarily
                    # leave the block at expected-1 and trip excess pruning
                    # on the wrong node).
                    registered = False
                    deadline = time.monotonic() + 5.0
                    while time.monotonic() < deadline:
                        locs_now = {d["u"] for d in
                                    self.nn.get_block_datanodes(
                                        block.to_wire())}
                        if target.uuid in locs_now:
                            registered = True
                            break
                        # bounded 5s poll for the IBR, not a retry
                        time.sleep(0.1)  # lint: disable=rpc/retry-no-backoff
                    if not registered:
                        # the new replica never reported: invalidating
                        # the old copy now would open a durability
                        # window for nothing — leave it for a later pass
                        log.warning("mover: new replica of %s on %s did "
                                    "not register; keeping the source",
                                    block, target.uuid)
                        continue
                    if self.nn.invalidate_replica(block.to_wire(),
                                                  bad.uuid):
                        moves += 1  # count only completed migrations
                    else:
                        # the NN's excess pruning (policy-aware) can
                        # retire the misplaced copy the instant the new
                        # replica registers — that race is still a
                        # completed migration; only an UNMOVED source
                        # is a failure
                        locs_now = {d["u"] for d in
                                    self.nn.get_block_datanodes(
                                        block.to_wire())}
                        if bad.uuid not in locs_now:
                            moves += 1
                except (OSError, IOError) as e:
                    log.warning("mover transfer %s failed: %s", block, e)
        return moves
