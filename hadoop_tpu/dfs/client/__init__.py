from hadoop_tpu.dfs.client.dfsclient import DFSClient
from hadoop_tpu.dfs.client.filesystem import DistributedFileSystem

__all__ = ["DFSClient", "DistributedFileSystem"]
