"""DFSClient: the client-side brain — NN proxy, leases, stream factories.

Parity with the reference (ref: hadoop-hdfs-client DFSClient.java:1155 create,
LeaseRenewer.java): holds the ClientProtocol proxy (wrapped in retry/failover),
a unique client name for lease identity, and a renewer thread that heartbeats
leases while any file is open for write.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc.errors import RpcError
from hadoop_tpu.dfs.client.streams import DFSInputStream, DFSOutputStream
from hadoop_tpu.dfs.protocol.records import (Block, FileStatus, LocatedBlock)
from hadoop_tpu.ipc import (Client, RetryInvocationHandler, RetryPolicies,
                            StaticFailoverProxyProvider, get_proxy)
from hadoop_tpu.util.misc import RETRY_RNG, Daemon

log = logging.getLogger(__name__)


class _ClientProtocolDecl:
    """Idempotency declarations for the proxy (mirrors the server's
    ClientProtocol annotations)."""
    from hadoop_tpu.ipc import idempotent as _idem

    @_idem
    def get_block_locations(self): ...
    @_idem
    def get_file_info(self): ...
    @_idem
    def listing(self): ...
    @_idem
    def content_summary(self): ...
    @_idem
    def renew_lease(self): ...
    @_idem
    def get_stats(self): ...
    @_idem
    def get_datanode_report(self): ...
    @_idem
    def get_service_status(self): ...
    @_idem
    def msync(self): ...
    @_idem
    def get_ec_policy(self): ...
    @_idem
    def get_ec_policies(self): ...
    @_idem
    def get_data_encryption_key(self): ...


class DFSClient:
    _counter = 0
    _counter_lock = threading.Lock()

    def __init__(self, nn_addrs, conf: Optional[Configuration] = None):
        """``nn_addrs``: one (host, port) or a list of them (HA failover)."""
        self.conf = conf or Configuration()
        if isinstance(nn_addrs, tuple):
            nn_addrs = [nn_addrs]
        self.nn_addrs = nn_addrs
        with DFSClient._counter_lock:
            DFSClient._counter += 1
            n = DFSClient._counter
        self.client_name = f"DFSClient_{os.getpid()}_{n}"
        self._rpc_client = Client(self.conf)
        provider = StaticFailoverProxyProvider(
            lambda addr: get_proxy("ClientProtocol", addr,
                                   client=self._rpc_client), nn_addrs)
        # Wrap idempotency info: RetryInvocationHandler asks the proxy; our
        # raw proxy has no class info, so patch _is_idempotent.
        self._decl = _ClientProtocolDecl
        policy = RetryPolicies.failover_on_network_exception(
            max_failovers=len(nn_addrs) * 4, delay_s=0.3)
        self.nn = _DeclaredRetryProxy(provider, policy, self._decl)
        # Observer reads (ref: namenode/ha/ObserverReadProxyProvider.java:70):
        # route idempotent calls to an observer NN, writes to the active; an
        # initial msync seeds the state id so observer reads are consistent.
        if self.conf.get_bool("dfs.client.observer.reads.enabled", False) \
                and len(nn_addrs) > 1:
            self.nn = _ObserverReadProxy(
                provider, policy, self._decl, self, nn_addrs)
        # Data-transfer encryption (ref: DFSClient's
        # SaslDataTransferClient under dfs.encrypt.data.transfer): fetch
        # the NN's current key and install the process dial-side default
        # so every data socket — pipeline, pread, striped, balancer —
        # handshakes before the first op frame.
        self.transfer_security = None
        if self.conf.get_bool("dfs.encrypt.data.transfer", False):
            from hadoop_tpu.dfs.protocol import datatransfer as dt
            self.transfer_security = dt.TransferSecurity(
                lambda: self.nn.get_data_encryption_key(),
                qop=self.conf.get("dfs.data.transfer.protection",
                                  "privacy"))
            dt.set_default_security(self.transfer_security)
        self._block_sizes: Dict[str, int] = {}
        self._hedged_pool = None
        self._hedged_pool_lock = threading.Lock()
        self._hedged_inflight = 0   # submitted, not yet finished
        self.hedged_reads = 0   # hedges started (metric parity:
        self.hedged_wins = 0    # DFSHedgedReadMetrics)
        self._open_files = 0
        self._renewer_lock = threading.Lock()
        self._renewer_stop: Optional[threading.Event] = None

    # ----------------------------------------------------------- streams

    def create(self, path: str, overwrite: bool = False,
               replication: Optional[int] = None,
               block_size: Optional[int] = None):
        st = FileStatus.from_wire(
            self.nn.create(path, self.client_name, replication, block_size,
                           overwrite))
        self._block_sizes[path] = st.block_size
        self._writer_opened()
        if st.ec_policy:
            from hadoop_tpu.dfs.client.striped import DFSStripedOutputStream
            stream = DFSStripedOutputStream(self, path, st.ec_policy)
        else:
            # ref: dfs.client-write-packet-size (DfsClientConf). The
            # reference defaults to 64 KB against spinning-disk-era acks;
            # here the per-packet cost is a Python thread handoff chain,
            # so the default is 1 MB and bulk writers can raise it.
            from hadoop_tpu.dfs.protocol import datatransfer as _dt
            pkt = self.conf.get_size_bytes(
                "dfs.client-write-packet-size", _dt.PACKET_SIZE)
            # ref: dfs.bytes-per-checksum — the replica's meta stores the
            # writer's chunking and read setup replies echo it back, so
            # any reader verifies with the right bpc
            bpc = self.conf.get_size_bytes(
                "dfs.bytes-per-checksum", _dt.CHUNK_SIZE)
            # Write-pipeline depth (STORAGE_BENCH showed writes at ~1/6
            # of read throughput; the pipe per hop held ~1 packet):
            # outstanding-ack window (ref: the reference's 80-packet
            # dataQueue bound) + per-hop socket buffer sizing.
            window = self.conf.get_int(
                "dfs.client.write.max-packets-in-flight", 64)
            sock_buf = self.conf.get_size_bytes(
                "dfs.client.write.socket.buffer", 0)
            stream = DFSOutputStream(self, path, packet_size=pkt,
                                     chunk_size=bpc,
                                     max_packets_in_flight=window,
                                     socket_buffer=sock_buf)
        orig_close = stream.close

        def close_and_release():
            try:
                orig_close()
            finally:
                self._writer_closed()
        stream.close = close_and_release  # type: ignore[method-assign]
        return stream

    def open(self, path: str):
        # One NN round trip: the located blocks carry the EC marker, so the
        # stream type is chosen from the same response the stream consumes.
        info = self.get_block_locations(path)
        blocks = info.get("blocks", [])
        if blocks and blocks[0].get("ec"):
            from hadoop_tpu.dfs.client.striped import DFSStripedInputStream
            return DFSStripedInputStream(self, path, info)
        return DFSInputStream(self, path, info)

    # ----------------------------------------------------- erasure coding

    def set_ec_policy(self, path: str, policy: Optional[str]) -> bool:
        return self.nn.set_ec_policy(path, policy)

    def get_ec_policy(self, path: str) -> Optional[str]:
        return self.nn.get_ec_policy(path)

    # ------------------------------------------------- stream callbacks

    def allocate_block(self, path: str, previous: Optional[Dict],
                       exclude: List[str]) -> LocatedBlock:
        return LocatedBlock.from_wire(
            self.nn.add_block(path, self.client_name, previous, exclude))

    def abandon_block(self, path: str, block: Block) -> None:
        self.nn.abandon_block(path, self.client_name, block.to_wire())

    def complete_file(self, path: str, last: Optional[Dict]) -> None:
        import time
        # Millisecond-scale early rungs: DNs enqueue the incremental
        # block report the moment a replica finalizes (immediate-IBR
        # wake in _BPServiceActor), so the NN usually learns of the
        # last block within a few ms of our final ack — the reference's
        # 400 ms initial delay (locateFollowingBlock.initial.delay.ms)
        # is sized for its heartbeat-batched IBR path, not this one.
        for backoff in (0.003, 0.01, 0.03, 0.1, 0.4, 0.8, 1.6, 3.2, 6.4):
            if self.nn.complete(path, self.client_name, last):
                return
            # jittered ladder (ref: DFSOutputStream.completeFile loop):
            # many writers closing together must not re-poll in phase
            time.sleep(backoff * (0.5 + RETRY_RNG.random()))
        raise IOError(f"could not complete {path}: min replication not met")

    def block_size_for(self, path: str) -> int:
        bs = self._block_sizes.get(path)
        if bs is None:
            st = FileStatus.from_wire(self.nn.get_file_info(path))
            bs = st.block_size
        return bs

    def get_block_locations(self, path: str) -> Dict:
        return self.nn.get_block_locations(path)

    def report_bad_block(self, block: Block, dn_uuid: str) -> None:
        try:
            self.nn.report_bad_blocks([block.to_wire()], [dn_uuid])
        except Exception as e:  # noqa: BLE001 — best effort
            log.debug("report_bad_blocks failed: %s", e)

    # ------------------------------------------------------ lease renewer

    def _writer_opened(self) -> None:
        with self._renewer_lock:
            self._open_files += 1
            if self._renewer_stop is None:
                self._renewer_stop = threading.Event()
                Daemon(self._renew_loop, f"lease-renewer-{self.client_name}"
                       ).start()

    def _writer_closed(self) -> None:
        with self._renewer_lock:
            self._open_files -= 1

    def _renew_loop(self) -> None:
        """Ref: LeaseRenewer.run — renew at half the soft limit."""
        interval = self.conf.get_time_seconds("dfs.lease.soft-limit", 60.0) / 2
        stop = self._renewer_stop
        while not stop.wait(min(interval, 2.0)):
            with self._renewer_lock:
                if self._open_files <= 0:
                    continue
            try:
                self.nn.renew_lease(self.client_name)
            except Exception as e:  # noqa: BLE001
                log.warning("lease renewal failed: %s", e)

    def hedged_pool(self):
        """Shared executor for hedged reads (ref: DFSClient
        .initThreadsNumForHedgedReads)."""
        with self._hedged_pool_lock:
            if self._hedged_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                from hadoop_tpu.conf.keys import (
                    DFS_CLIENT_HEDGED_READ_POOL_SIZE,
                    DFS_CLIENT_HEDGED_READ_POOL_SIZE_DEFAULT)
                size = self.conf.get_int(
                    DFS_CLIENT_HEDGED_READ_POOL_SIZE,
                    DFS_CLIENT_HEDGED_READ_POOL_SIZE_DEFAULT)
                # only reached when streams saw a nonzero pool size;
                # clamp so a racing reconfigure still gets a live pool
                self._hedged_workers = max(2, size)
                self._hedged_pool = ThreadPoolExecutor(
                    max_workers=self._hedged_workers,
                    thread_name_prefix="hedged-read")
            return self._hedged_pool

    def hedged_submit(self, fn, *args):
        """Submit a hedged task tracking in-flight count, or None when
        the pool is saturated by straggling losers — the caller falls
        back to its sequential path instead of queueing a NEW read
        behind stuck threads (the reference gets the same property from
        a SynchronousQueue + CallerRunsPolicy)."""
        pool = self.hedged_pool()
        with self._hedged_pool_lock:
            if self._hedged_inflight >= self._hedged_workers:
                return None
            self._hedged_inflight += 1
        # span-aware seam: the pool thread reads the SUBMITTING thread's
        # active span, so a hedge's DN read joins the read's trace
        from hadoop_tpu.tracing.tracer import carry_context
        fut = pool.submit(carry_context(fn), *args)

        def _done(_f):
            with self._hedged_pool_lock:
                self._hedged_inflight -= 1
        fut.add_done_callback(_done)
        return fut

    def close(self) -> None:
        if self._renewer_stop is not None:
            self._renewer_stop.set()
        self._rpc_client.stop()
        if self._hedged_pool is not None:
            self._hedged_pool.shutdown(wait=False)
        if self.transfer_security is not None:
            from hadoop_tpu.dfs.protocol import datatransfer as dt
            # Uninstall only if still ours: a newer client may have
            # replaced the process default.
            if dt.default_security() is self.transfer_security:
                dt.set_default_security(None)


_OBSERVER_READS = frozenset({
    # Pure namespace reads an observer may serve (ref: the @ReadOnly
    # annotations ObserverReadProxyProvider honors). renew_lease and
    # report_bad_blocks are idempotent but mutate active-side state.
    "get_block_locations", "get_file_info", "listing", "content_summary",
    "get_stats", "get_datanode_report", "get_ec_policy", "get_ec_policies",
})


class _ObserverReadProxy:
    """Ref: ObserverReadProxyProvider.java — read-only calls try an
    observer first (with state-id alignment carried by the RPC layer);
    everything else, and any observer failure, goes through the normal
    active-failover proxy."""

    def __init__(self, provider, policy, decl_cls, client: "DFSClient",
                 nn_addrs):
        self._active = _DeclaredRetryProxy(provider, policy, decl_cls)
        self._decl = decl_cls
        self._client = client
        self._addrs = nn_addrs
        self._observer = None
        self._probed = False
        self._synced = False

    def _find_observer(self):
        from hadoop_tpu.ipc import get_proxy
        for addr in self._addrs:
            try:
                proxy = get_proxy("ClientProtocol", addr,
                                  client=self._client._rpc_client)
                st = proxy.get_service_status()
                if st.get("state") == "observer":
                    log.info("Observer reads via %s", addr)
                    return proxy
            except Exception:  # noqa: BLE001 — not an observer / down
                continue
        return None

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            if name in _OBSERVER_READS:
                if not self._synced:
                    # Seed the client state id from the active so the first
                    # observer read already waits for current state.
                    try:
                        self._active.msync()
                        self._synced = True
                    except (RpcError, OSError) as e:
                        log.debug("msync to active failed: %s", e)
                if not self._probed:
                    self._observer = self._find_observer()
                    self._probed = True
                if self._observer is not None:
                    try:
                        return getattr(self._observer, name)(*args, **kwargs)
                    except Exception as e:  # noqa: BLE001 — fall to active
                        log.debug("observer read %s failed (%s); using "
                                  "active", name, e)
                        self._observer = None
                        self._probed = False
            return getattr(self._active, name)(*args, **kwargs)

        return call


class _DeclaredRetryProxy(RetryInvocationHandler):
    """RetryInvocationHandler whose idempotency comes from a declaration
    class rather than the remote proxy object."""

    def __init__(self, provider, policy, decl_cls):
        super().__init__(provider, policy)
        self._decl_cls = decl_cls

    def invoke(self, method_name: str, *args, **kwargs):
        retries = 0
        failovers = 0
        import time as _time
        idem = bool(getattr(getattr(self._decl_cls, method_name, None),
                            "_rpc_idempotent", False))
        while True:
            proxy = self.provider.get_proxy()
            try:
                set_rc = getattr(proxy, "_set_retry_count", None)
                if set_rc:
                    set_rc(retries)
                return getattr(proxy, method_name)(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — policy decides
                action = self.policy.should_retry(e, retries, failovers, idem)
                from hadoop_tpu.ipc.retry import RetryAction
                if action.action == RetryAction.FAIL:
                    raise
                if action.delay_s > 0:
                    _time.sleep(action.delay_s)
                if action.action == RetryAction.FAILOVER_AND_RETRY:
                    self.provider.perform_failover(proxy)
                    failovers += 1
                retries += 1
