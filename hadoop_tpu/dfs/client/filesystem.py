"""DistributedFileSystem: the FileSystem SPI face of the DFS.

Parity with the reference (ref: hadoop-hdfs-client
DistributedFileSystem.java:486 create — 3,626 LoC): thin adapter from the
FileSystem contract onto DFSClient. Registered under scheme ``htpu``
(the hdfs:// analog).
"""

from __future__ import annotations

from typing import List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.client.dfsclient import DFSClient
from hadoop_tpu.dfs.protocol.records import FileStatus
from hadoop_tpu.fs.filesystem import FileSystem, Path, register_filesystem


class DistributedFileSystem(FileSystem):
    def __init__(self, nn_addrs, conf: Optional[Configuration] = None):
        self.client = DFSClient(nn_addrs, conf)

    @classmethod
    def create_instance(cls, path: Path, conf: Configuration):
        if path.authority:
            host, _, port = path.authority.partition(":")
            addrs = [(host, int(port))]
        else:
            addrs = [tuple(a.rsplit(":", 1))
                     for a in conf.get_list("dfs.namenode.rpc-address")]
            addrs = [(h, int(p)) for h, p in addrs]
        return cls(addrs, conf)

    def open(self, path: str):
        return self.client.open(path)

    def create(self, path: str, overwrite: bool = False, replication=None,
               block_size=None):
        return self.client.create(path, overwrite=overwrite,
                                  replication=replication,
                                  block_size=block_size)

    def mkdirs(self, path: str) -> bool:
        return self.client.nn.mkdirs(path)

    def delete(self, path: str, recursive: bool = False) -> bool:
        return self.client.nn.delete(path, recursive)

    def rename(self, src: str, dst: str) -> bool:
        return self.client.nn.rename(src, dst)

    def list_status(self, path: str) -> List[FileStatus]:
        return [FileStatus.from_wire(d) for d in self.client.nn.listing(path)]

    def get_file_status(self, path: str) -> FileStatus:
        info = self.client.nn.get_file_info(path)
        if info is None:
            raise FileNotFoundError(path)
        return FileStatus.from_wire(info)

    def set_replication(self, path: str, replication: int) -> bool:
        return self.client.nn.set_replication(path, replication)

    def content_summary(self, path: str):
        return self.client.nn.content_summary(path)

    def close(self) -> None:
        self.client.close()


register_filesystem("htpu", DistributedFileSystem)
