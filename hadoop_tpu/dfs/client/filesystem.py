"""DistributedFileSystem: the FileSystem SPI face of the DFS.

Parity with the reference (ref: hadoop-hdfs-client
DistributedFileSystem.java:486 create — 3,626 LoC): thin adapter from the
FileSystem contract onto DFSClient. Registered under scheme ``htpu``
(the hdfs:// analog).
"""

from __future__ import annotations

from typing import List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.client.dfsclient import DFSClient
from hadoop_tpu.dfs.protocol.records import FileStatus
from hadoop_tpu.fs.filesystem import FileSystem, Path, register_filesystem


class DistributedFileSystem(FileSystem):
    def __init__(self, nn_addrs, conf: Optional[Configuration] = None):
        self.client = DFSClient(nn_addrs, conf)
        self._kms_provider = None

    # ------------------------------------------------- encryption at rest

    def _kms(self):
        uri = self.client.conf.get("dfs.encryption.key.provider.uri", "")
        if not uri:
            return None
        if self._kms_provider is None:
            from hadoop_tpu.crypto.kms import KMSKeyProvider
            from hadoop_tpu.security.ugi import current_user
            self._kms_provider = KMSKeyProvider(
                uri.split("://", 1)[-1].rstrip("/"),
                user=current_user().user_name)
        return self._kms_provider

    def _dek_for(self, path: str):
        """(dek, iv) for an encrypted file, or None. Ref:
        HdfsKMSUtil.decryptEncryptedDataEncryptionKey — the client, not
        the NameNode, resolves EDEK→DEK so plaintext keys never touch
        the metadata plane."""
        info = self.client.nn.get_encryption_info(path)
        if info is None:
            return None
        kms = self._kms()
        if kms is None:
            raise PermissionError(
                f"{path} is in an encryption zone but this client has no "
                "KMS configured (dfs.encryption.key.provider.uri)")
        import base64 as _b64
        from hadoop_tpu.crypto.keys import EncryptedKeyVersion
        ekv = EncryptedKeyVersion(
            info["key"], info["version"], _b64.b64decode(info["iv"]),
            _b64.b64decode(info["edek"]))
        return kms.decrypt_encrypted_key(ekv), ekv.iv

    def create_encryption_zone(self, path: str, key_name: str) -> bool:
        return self.client.nn.create_encryption_zone(path, key_name)

    # ---------------------------------------------------- centralized cache

    def add_cache_directive(self, path: str) -> int:
        return self.client.nn.add_cache_directive(path)

    def remove_cache_directive(self, directive_id: int) -> bool:
        return self.client.nn.remove_cache_directive(directive_id)

    def list_cache_directives(self):
        return {int(k): v
                for k, v in self.client.nn.list_cache_directives().items()}

    def get_encryption_info(self, path: str):
        return self.client.nn.get_encryption_info(path)

    def list_encryption_zones(self):
        return self.client.nn.list_encryption_zones()

    @classmethod
    def create_instance(cls, path: Path, conf: Configuration):
        if path.authority:
            host, _, port = path.authority.partition(":")
            addrs = [(host, int(port))]
        else:
            from hadoop_tpu.conf.keys import (
                DFS_NAMENODE_RPC_ADDRESS,
                DFS_NAMENODE_RPC_ADDRESS_DEFAULT)
            from hadoop_tpu.util.misc import parse_addr_list
            addrs = parse_addr_list(conf.get(
                DFS_NAMENODE_RPC_ADDRESS,
                DFS_NAMENODE_RPC_ADDRESS_DEFAULT))
        return cls(addrs, conf)

    def open(self, path: str):
        stream = self.client.open(path)
        dek_iv = self._dek_for(path) if self._kms() is not None else None
        if dek_iv is not None:
            from hadoop_tpu.crypto.streams import CryptoInputStream
            return CryptoInputStream(stream, dek_iv[0], dek_iv[1])
        return stream

    def create(self, path: str, overwrite: bool = False, replication=None,
               block_size=None):
        stream = self.client.create(path, overwrite=overwrite,
                                    replication=replication,
                                    block_size=block_size)
        dek_iv = self._dek_for(path) if self._kms() is not None else None
        if dek_iv is not None:
            from hadoop_tpu.crypto.streams import CryptoOutputStream
            return CryptoOutputStream(stream, dek_iv[0], dek_iv[1])
        return stream

    def mkdirs(self, path: str) -> bool:
        return self.client.nn.mkdirs(path)

    def delete(self, path: str, recursive: bool = False) -> bool:
        return self.client.nn.delete(path, recursive)

    def rename(self, src: str, dst: str) -> bool:
        return self.client.nn.rename(src, dst)

    def list_status(self, path: str) -> List[FileStatus]:
        return [FileStatus.from_wire(d) for d in self.client.nn.listing(path)]

    def get_file_status(self, path: str) -> FileStatus:
        info = self.client.nn.get_file_info(path)
        if info is None:
            raise FileNotFoundError(path)
        return FileStatus.from_wire(info)

    def set_replication(self, path: str, replication: int) -> bool:
        return self.client.nn.set_replication(path, replication)

    def set_permission(self, path: str, permission: int) -> None:
        self.client.nn.set_permission(path, permission)

    def set_owner(self, path: str, owner: str, group: str) -> None:
        self.client.nn.set_owner(path, owner, group)

    # ------------------------------------------------- namespace features

    def set_quota(self, path: str, ns_quota: int = -1,
                  space_quota: int = -1) -> bool:
        return self.client.nn.set_quota(path, ns_quota, space_quota)

    def set_xattr(self, path: str, name: str, value: bytes) -> bool:
        return self.client.nn.set_xattr(path, name, value)

    def get_xattrs(self, path: str, names=None):
        return self.client.nn.get_xattrs(path, names)

    def remove_xattr(self, path: str, name: str) -> bool:
        return self.client.nn.remove_xattr(path, name)

    def set_acl(self, path: str, entries) -> bool:
        return self.client.nn.set_acl(path, entries)

    def get_acl(self, path: str):
        return self.client.nn.get_acl(path)

    def set_storage_policy(self, path: str, policy: str) -> bool:
        return self.client.nn.set_storage_policy(path, policy)

    def get_storage_policy(self, path: str) -> str:
        return self.client.nn.get_storage_policy(path)

    def allow_snapshot(self, path: str) -> bool:
        return self.client.nn.allow_snapshot(path)

    def create_snapshot(self, path: str, name: str) -> str:
        return self.client.nn.create_snapshot(path, name)

    def delete_snapshot(self, path: str, name: str) -> bool:
        return self.client.nn.delete_snapshot(path, name)

    def rename_snapshot(self, path: str, old: str, new: str) -> bool:
        return self.client.nn.rename_snapshot(path, old, new)

    def snapshot_diff(self, path: str, from_snap: str, to_snap: str):
        return self.client.nn.snapshot_diff(path, from_snap, to_snap)

    def concat(self, target: str, srcs) -> bool:
        return self.client.nn.concat(target, srcs)

    def truncate(self, path: str, new_length: int) -> bool:
        return self.client.nn.truncate(path, new_length)

    def content_summary(self, path: str):
        return self.client.nn.content_summary(path)

    def close(self) -> None:
        self.client.close()


register_filesystem("htpu", DistributedFileSystem)
