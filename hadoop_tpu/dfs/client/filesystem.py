"""DistributedFileSystem: the FileSystem SPI face of the DFS.

Parity with the reference (ref: hadoop-hdfs-client
DistributedFileSystem.java:486 create — 3,626 LoC): thin adapter from the
FileSystem contract onto DFSClient. Registered under scheme ``htpu``
(the hdfs:// analog).
"""

from __future__ import annotations

from typing import List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.client.dfsclient import DFSClient
from hadoop_tpu.dfs.protocol.records import FileStatus
from hadoop_tpu.fs.filesystem import FileSystem, Path, register_filesystem


class DistributedFileSystem(FileSystem):
    def __init__(self, nn_addrs, conf: Optional[Configuration] = None):
        self.client = DFSClient(nn_addrs, conf)

    @classmethod
    def create_instance(cls, path: Path, conf: Configuration):
        if path.authority:
            host, _, port = path.authority.partition(":")
            addrs = [(host, int(port))]
        else:
            addrs = [tuple(a.rsplit(":", 1))
                     for a in conf.get_list("dfs.namenode.rpc-address")]
            addrs = [(h, int(p)) for h, p in addrs]
        return cls(addrs, conf)

    def open(self, path: str):
        return self.client.open(path)

    def create(self, path: str, overwrite: bool = False, replication=None,
               block_size=None):
        return self.client.create(path, overwrite=overwrite,
                                  replication=replication,
                                  block_size=block_size)

    def mkdirs(self, path: str) -> bool:
        return self.client.nn.mkdirs(path)

    def delete(self, path: str, recursive: bool = False) -> bool:
        return self.client.nn.delete(path, recursive)

    def rename(self, src: str, dst: str) -> bool:
        return self.client.nn.rename(src, dst)

    def list_status(self, path: str) -> List[FileStatus]:
        return [FileStatus.from_wire(d) for d in self.client.nn.listing(path)]

    def get_file_status(self, path: str) -> FileStatus:
        info = self.client.nn.get_file_info(path)
        if info is None:
            raise FileNotFoundError(path)
        return FileStatus.from_wire(info)

    def set_replication(self, path: str, replication: int) -> bool:
        return self.client.nn.set_replication(path, replication)

    def set_permission(self, path: str, permission: int) -> None:
        self.client.nn.set_permission(path, permission)

    def set_owner(self, path: str, owner: str, group: str) -> None:
        self.client.nn.set_owner(path, owner, group)

    # ------------------------------------------------- namespace features

    def set_quota(self, path: str, ns_quota: int = -1,
                  space_quota: int = -1) -> bool:
        return self.client.nn.set_quota(path, ns_quota, space_quota)

    def set_xattr(self, path: str, name: str, value: bytes) -> bool:
        return self.client.nn.set_xattr(path, name, value)

    def get_xattrs(self, path: str, names=None):
        return self.client.nn.get_xattrs(path, names)

    def remove_xattr(self, path: str, name: str) -> bool:
        return self.client.nn.remove_xattr(path, name)

    def set_acl(self, path: str, entries) -> bool:
        return self.client.nn.set_acl(path, entries)

    def get_acl(self, path: str):
        return self.client.nn.get_acl(path)

    def set_storage_policy(self, path: str, policy: str) -> bool:
        return self.client.nn.set_storage_policy(path, policy)

    def get_storage_policy(self, path: str) -> str:
        return self.client.nn.get_storage_policy(path)

    def allow_snapshot(self, path: str) -> bool:
        return self.client.nn.allow_snapshot(path)

    def create_snapshot(self, path: str, name: str) -> str:
        return self.client.nn.create_snapshot(path, name)

    def delete_snapshot(self, path: str, name: str) -> bool:
        return self.client.nn.delete_snapshot(path, name)

    def rename_snapshot(self, path: str, old: str, new: str) -> bool:
        return self.client.nn.rename_snapshot(path, old, new)

    def snapshot_diff(self, path: str, from_snap: str, to_snap: str):
        return self.client.nn.snapshot_diff(path, from_snap, to_snap)

    def concat(self, target: str, srcs) -> bool:
        return self.client.nn.concat(target, srcs)

    def truncate(self, path: str, new_length: int) -> bool:
        return self.client.nn.truncate(path, new_length)

    def content_summary(self, path: str):
        return self.client.nn.content_summary(path)

    def close(self) -> None:
        self.client.close()


register_filesystem("htpu", DistributedFileSystem)
