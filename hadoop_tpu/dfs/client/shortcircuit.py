"""Short-circuit local reads — same-host replicas bypass the DN data path.

Parity with the reference's short-circuit read stack (ref:
hadoop-hdfs-client/.../shortcircuit/ShortCircuitCache.java:72,
ShortCircuitShm.java, client/impl/BlockReaderFactory.java:354-381
getBlockReaderLocal; native transport
hadoop-common/src/main/native/src/org/apache/hadoop/net/unix/DomainSocket.c):
when a replica lives on the reader's own host, the client asks the DN once
for the replica's file layout and from then on reads the block file
directly — no socket hop, no DN thread, no packet framing — while STILL
verifying the stored CRCs (BlockReaderLocal does the same; skipping
verification is a separate opt-in there).

Transport simplification: the reference passes open file descriptors over
a Unix domain socket so the DN never reveals paths; here the DN hands the
client the replica's (data, meta) paths over the regular transfer port.
Same trust domain (one OS user runs both on a TPU-VM host), one fewer
native layer. The cache keys and invalidation rules mirror
ShortCircuitCache: cached per (block, genstamp), dropped on any IO error
so the TCP path takes over (e.g. after the balancer moves a replica).
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import Optional, Tuple

from hadoop_tpu.dfs.protocol import datatransfer as dt
from hadoop_tpu.dfs.protocol.records import Block, DatanodeInfo
from hadoop_tpu.util.crc import DataChecksum
from hadoop_tpu.util.misc import local_host_names

log = logging.getLogger(__name__)


class ShortCircuitUnavailable(Exception):
    """Fall back to the TCP reader (DN too old, replica moved, ...)."""


class _Slot:
    __slots__ = ("data_path", "meta_path", "bpc", "visible")

    def __init__(self, data_path: str, meta_path: str, bpc: int,
                 visible: int):
        self.data_path = data_path
        self.meta_path = meta_path
        self.bpc = bpc
        self.visible = visible


class ShortCircuitCache:
    """Per-process replica-layout cache, LRU-bounded (the reference's
    ShortCircuitCache evicts on expiry; a size cap serves the same
    goal — a long-lived reader must not accumulate a slot per block it
    ever touched). Ref: ShortCircuitCache.java:72."""

    MAX_SLOTS = 4096  # ~a few hundred KB of path strings at the cap

    _instance: Optional["ShortCircuitCache"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._slots: "collections.OrderedDict[Tuple, _Slot]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._local = local_host_names()
        self.hits = 0
        self.requests = 0

    @classmethod
    def get(cls) -> "ShortCircuitCache":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def is_local(self, dn: DatanodeInfo) -> bool:
        return dn.host in self._local

    # ------------------------------------------------------------ plumbing

    def _slot_for(self, dn: DatanodeInfo, block: Block) -> _Slot:
        # keyed per REPLICA (dn included): every same-host DN holds its own
        # copy, and a corrupt copy must not shadow the healthy ones
        key = (dn.uuid, block.block_id, block.gen_stamp)
        with self._lock:
            slot = self._slots.get(key)
            if slot is not None:
                self._slots.move_to_end(key)
        if slot is not None:
            return slot
        self.requests += 1
        sock = dt.connect(dn.xfer_addr(), timeout=10.0)
        try:
            dt.send_frame(sock, {"op": dt.OP_SHORT_CIRCUIT,
                                 "b": block.to_wire()})
            resp = dt.recv_frame(sock)
        finally:
            sock.close()
        if not resp.get("ok"):
            raise ShortCircuitUnavailable(resp.get("em", "refused"))
        slot = _Slot(resp["data_path"], resp["meta_path"], resp["bpc"],
                     resp["visible"])
        with self._lock:
            self._slots[key] = slot
            self._slots.move_to_end(key)
            while len(self._slots) > self.MAX_SLOTS:
                self._slots.popitem(last=False)
        return slot

    def invalidate(self, block: Block, dn: Optional[DatanodeInfo] = None
                   ) -> None:
        with self._lock:
            for key in [k for k in self._slots
                        if k[1] == block.block_id
                        and k[2] == block.gen_stamp
                        and (dn is None or k[0] == dn.uuid)]:
                del self._slots[key]

    # ---------------------------------------------------------------- read

    META_HEADER = 4 + 8 + DataChecksum.HEADER_LEN

    def read(self, dn: DatanodeInfo, block: Block, offset: int,
             want: int) -> bytes:
        """Read [offset, offset+want) of a local replica, CRC-verified.
        Raises ShortCircuitUnavailable to punt to the TCP reader; raises
        ChecksumError (like the remote path) on real corruption."""
        slot = self._slot_for(dn, block)
        try:
            bpc = slot.bpc
            avail = min(want, slot.visible - offset)
            if avail <= 0:
                return b""
            # chunk-align both edges: stored CRCs cover whole chunks
            start = (offset // bpc) * bpc
            end = min(slot.visible,
                      (offset + avail + bpc - 1) // bpc * bpc)
            with open(slot.data_path, "rb") as df:
                df.seek(start)
                data = df.read(end - start)
            first_chunk = start // bpc
            n_chunks = (len(data) + bpc - 1) // bpc
            with open(slot.meta_path, "rb") as mf:
                mf.seek(self.META_HEADER + 4 * first_chunk)
                sums = mf.read(4 * n_chunks)
        except OSError as e:
            # replica moved/deleted under us — forget it, use TCP
            self.invalidate(block, dn)
            raise ShortCircuitUnavailable(str(e)) from e
        try:
            DataChecksum(bpc).verify(data, sums, base_pos=start)
        except Exception:
            self.invalidate(block, dn)  # corrupt copy: never re-serve it
            raise
        self.hits += 1
        return data[offset - start:offset - start + avail]
