"""Short-circuit local reads — fd-passing grants, no DN data path.

Parity with the reference's short-circuit read stack (ref:
hadoop-hdfs-client/.../shortcircuit/ShortCircuitCache.java:72,
client/impl/BlockReaderFactory.java:354-381 getBlockReaderLocal;
native transport hadoop-common/.../net/unix/DomainSocket.c): when a
replica lives on the reader's own host, the client asks the DN's
AF_UNIX socket for the replica's OPEN file descriptors (SCM_RIGHTS,
``socket.recv_fds``) and from then on reads the block file directly —
no socket hop, no DN thread, no packet framing — while STILL verifying
the stored CRCs (BlockReaderLocal does the same).

Security: the grant is gated on the block access token when
``dfs.block.access.token.enable`` is on — the DN never reveals paths,
so a client that could not read the block over the authenticated TCP
path cannot open the replica locally either (this replaces the round-4
path-handoff shortcut the advisor flagged as inconsistent).

Socket discovery: the ``dfs.domain.socket.path`` template with the
reference's ``_PORT`` placeholder, expanded with the DN's transfer
port; when the client conf lacks it, one TCP round-trip to the DN's
transfer port learns the path (the reply carries ``domain_socket``).

Cache/invalidation mirror ShortCircuitCache: slots key per
(dn, block, genstamp); LRU-evicted slots close their fds; any IO or
checksum error drops the slot so the TCP path takes over. A cached fd
stays valid across DN restarts and replica moves — finalized block
bytes at a given genstamp are immutable, and append/recovery bumps the
genstamp into a different cache key.
"""

from __future__ import annotations

import array
import collections
import logging
import os
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

from hadoop_tpu.dfs.protocol import datatransfer as dt
from hadoop_tpu.dfs.protocol.records import Block, DatanodeInfo
from hadoop_tpu.io import pack, unpack
from hadoop_tpu.util.crc import DataChecksum
from hadoop_tpu.util.misc import local_host_names

log = logging.getLogger(__name__)


class ShortCircuitUnavailable(Exception):
    """Fall back to the TCP reader (no domain socket, token refused at
    discovery time, replica moved, ...)."""


class _Slot:
    """Refcounted fd pair (ref: ShortCircuitCache's slot refcounting):
    eviction/invalidation must never close descriptors a concurrent
    read() still holds — a reused fd number would make that reader
    pread ANOTHER block's bytes and report a healthy replica corrupt.
    ``refs``/``dead`` transitions happen under the cache lock; the last
    releaser closes."""

    __slots__ = ("data_fd", "meta_fd", "bpc", "visible", "refs", "dead")

    def __init__(self, data_fd: int, meta_fd: int, bpc: int, visible: int):
        self.data_fd = data_fd
        self.meta_fd = meta_fd
        self.bpc = bpc
        self.visible = visible
        self.refs = 0
        self.dead = False

    def _close_now(self) -> None:
        for fd in (self.data_fd, self.meta_fd):
            try:
                os.close(fd)
            except OSError:
                pass
        self.data_fd = self.meta_fd = -1


class ShortCircuitCache:
    """Per-process replica-fd cache, LRU-bounded (ref:
    ShortCircuitCache.java:72 — it caches replica slots the same way;
    the size cap bounds open-fd usage at 2×MAX_SLOTS descriptors)."""

    MAX_SLOTS = 256

    _instance: Optional["ShortCircuitCache"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._slots: "collections.OrderedDict[Tuple, _Slot]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._local = local_host_names()
        self._socket_paths: Dict[str, str] = {}   # dn uuid → AF_UNIX path
        self.hits = 0
        self.requests = 0

    @classmethod
    def get(cls) -> "ShortCircuitCache":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def is_local(self, dn: DatanodeInfo) -> bool:
        return dn.host in self._local

    # ------------------------------------------------------------ plumbing

    def _socket_path(self, dn: DatanodeInfo, template: str) -> str:
        if template:
            return template.replace("_PORT", str(dn.xfer_port))
        path = self._socket_paths.get(dn.uuid)
        if path:
            return path
        # one-time TCP discovery: the DN advertises its domain socket
        sock = dt.connect(dn.xfer_addr(), timeout=10.0)
        try:
            dt.send_frame(sock, {"op": dt.OP_SHORT_CIRCUIT})
            resp = dt.recv_frame(sock)
        finally:
            sock.close()
        path = resp.get("domain_socket") or ""
        if not path:
            raise ShortCircuitUnavailable(
                resp.get("em", "DN offers no domain socket"))
        self._socket_paths[dn.uuid] = path
        return path

    def _request_fds(self, path: str, block: Block,
                     token: Optional[Dict]) -> _Slot:
        """REQUEST_FDS over AF_UNIX; fds arrive via SCM_RIGHTS."""
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        fds: list = []
        try:
            sock.settimeout(10.0)
            try:
                sock.connect(path)
            except OSError as e:
                raise ShortCircuitUnavailable(
                    f"domain socket {path}: {e}") from e
            req = {"b": block.to_wire()}
            if token is not None:
                req["tok"] = token
            frame = pack(req)
            sock.sendall(struct.pack(">I", len(frame)) + frame)
            # the reply frame and the fds ride one sendmsg; drain until
            # the full length-prefixed frame is in hand
            buf = bytearray()
            while len(buf) < 4:
                chunk, newfds, _, _ = socket.recv_fds(sock, 1 << 16, 2)
                if not chunk and not newfds:
                    raise ShortCircuitUnavailable("DN closed fd channel")
                fds.extend(newfds)
                buf += chunk
            (flen,) = struct.unpack_from(">I", buf)
            while len(buf) < 4 + flen:
                chunk, newfds, _, _ = socket.recv_fds(sock, 1 << 16, 2)
                if not chunk and not newfds:
                    break
                fds.extend(newfds)
                buf += chunk
            if len(buf) < 4 + flen:
                # DN died mid-reply; a truncated frame must degrade to
                # the TCP path, not surface a decode error to read()
                raise ShortCircuitUnavailable(
                    f"truncated fd-grant reply ({len(buf)}/{4 + flen}B)")
            try:
                resp = unpack(bytes(buf[4:4 + flen]))
            except Exception as e:  # WireError/garbage: same degrade
                raise ShortCircuitUnavailable(
                    f"undecodable fd-grant reply: {e}") from e
            if not resp.get("ok"):
                raise ShortCircuitUnavailable(resp.get("em", "refused"))
            if len(fds) != 2 or "bpc" not in resp or "visible" not in resp:
                raise ShortCircuitUnavailable(
                    f"malformed fd grant (fds={len(fds)})")
            bpc = resp["bpc"]
            if not isinstance(bpc, int) or not 0 < bpc <= (1 << 20):
                raise ShortCircuitUnavailable(
                    f"fd grant carries invalid bytes-per-checksum {bpc!r}")
            slot = _Slot(fds[0], fds[1], bpc, resp["visible"])
            fds = []  # ownership moved into the slot
            return slot
        finally:
            for fd in fds:
                try:
                    os.close(fd)
                except OSError:
                    pass
            sock.close()

    def _slot_for(self, dn: DatanodeInfo, block: Block,
                  token: Optional[Dict], template: str) -> _Slot:
        # keyed per REPLICA (dn included): every same-host DN holds its own
        # copy, and a corrupt copy must not shadow the healthy ones
        key = (dn.uuid, block.block_id, block.gen_stamp)
        with self._lock:
            slot = self._slots.get(key)
            if slot is not None:
                self._slots.move_to_end(key)
                self._acquire_locked(slot)
        if slot is not None:
            return slot
        self.requests += 1
        try:
            path = self._socket_path(dn, template)
            slot = self._request_fds(path, block, token)
        except ShortCircuitUnavailable:
            # a discovered socket path may be stale (DN restarted onto a
            # new path) — drop it so the next attempt rediscovers
            # instead of paying a failing connect forever
            self._socket_paths.pop(dn.uuid, None)
            raise
        with self._lock:
            have = self._slots.get(key)
            if have is not None:
                # lost a race: keep the existing slot, drop ours
                self._retire_locked(slot)
                slot = have
            else:
                self._slots[key] = slot
            self._slots.move_to_end(key)
            self._acquire_locked(slot)
            while len(self._slots) > self.MAX_SLOTS:
                self._retire_locked(self._slots.popitem(last=False)[1])
        return slot

    def _acquire_locked(self, slot: _Slot) -> None:
        slot.refs += 1

    def _release(self, slot: _Slot) -> None:
        with self._lock:
            slot.refs -= 1
            if slot.dead and slot.refs == 0:
                slot._close_now()

    def _retire_locked(self, slot: _Slot) -> None:
        slot.dead = True
        if slot.refs == 0:
            slot._close_now()

    def invalidate(self, block: Block, dn: Optional[DatanodeInfo] = None
                   ) -> None:
        with self._lock:
            for key in [k for k in self._slots
                        if k[1] == block.block_id
                        and k[2] == block.gen_stamp
                        and (dn is None or k[0] == dn.uuid)]:
                self._retire_locked(self._slots.pop(key))

    # ---------------------------------------------------------------- read

    META_HEADER = 4 + 8 + DataChecksum.HEADER_LEN

    def read(self, dn: DatanodeInfo, block: Block, offset: int,
             want: int, token: Optional[Dict] = None,
             socket_template: str = "") -> bytes:
        """Read [offset, offset+want) of a local replica, CRC-verified.
        Raises ShortCircuitUnavailable to punt to the TCP reader; raises
        ChecksumError (like the remote path) on real corruption."""
        slot = self._slot_for(dn, block, token, socket_template)
        try:
            try:
                bpc = slot.bpc
                avail = min(want, slot.visible - offset)
                if avail <= 0:
                    return b""
                # chunk-align both edges: stored CRCs cover whole chunks
                start = (offset // bpc) * bpc
                end = min(slot.visible,
                          (offset + avail + bpc - 1) // bpc * bpc)
                data = os.pread(slot.data_fd, end - start, start)
                first_chunk = start // bpc
                n_chunks = (len(data) + bpc - 1) // bpc
                sums = os.pread(slot.meta_fd, 4 * n_chunks,
                                self.META_HEADER + 4 * first_chunk)
            except OSError as e:
                # fd went bad under us — forget it, use TCP
                self.invalidate(block, dn)
                raise ShortCircuitUnavailable(str(e)) from e
            try:
                DataChecksum(bpc).verify(data, sums, base_pos=start)
            except Exception:
                self.invalidate(block, dn)  # corrupt copy: never re-serve
                raise
            self.hits += 1
            return data[offset - start:offset - start + avail]
        finally:
            self._release(slot)
