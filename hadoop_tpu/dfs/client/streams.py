"""Client streams: pipelined block writes, checksum-verified failover reads.

Write path parity (ref: hadoop-hdfs-client DFSOutputStream.java:263
newStreamForCreate, DataStreamer.java:116/:655 run/:1656
nextBlockOutputStream/:872 waitForAckedSeqno, FSOutputSummer.java): the app
thread chunks bytes into 64 KB packets with per-512B CRCs onto a bounded data
queue; the DataStreamer thread allocates blocks (add_block RPC with an
exclude list), builds the DN pipeline, streams packets, and a
ResponseProcessor consumes pipeline acks.

Pipeline failure handling: a failed setup excludes the reported bad node and
re-allocates (ref: nextBlockOutputStream's abandonBlock+retry loop). A
mid-block failure re-sends the whole current block through a fresh pipeline —
packets of the active block are retained until the block completes, so the
recovery window is one block (the reference instead replays only unacked
packets onto the surviving DNs with a new generation stamp
[DataStreamer error paths + updatePipeline]; same durability contract, at
the cost of a block-sized rather than window-sized client buffer).

Read path parity (ref: DFSInputStream.java:639 blockSeekTo / :724
getBlockReader, BlockReaderFactory.java:88): per-block location list from the
NN (NN pre-shuffles), CRC verification per packet, dead-node marking and
next-replica failover; corrupt replicas are reported back to the NN.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, List, Optional, Set

from hadoop_tpu.dfs.protocol import datatransfer as dt
from hadoop_tpu.ipc.errors import RpcError
from hadoop_tpu.dfs.protocol.records import Block, DatanodeInfo, LocatedBlock
from hadoop_tpu.tracing.tracer import current_context, global_tracer
from hadoop_tpu.util.crc import ChecksumError, DataChecksum
from hadoop_tpu.util.misc import backoff_delay

log = logging.getLogger(__name__)


class _Packet:
    __slots__ = ("seq", "offset", "data", "sums", "last")

    def __init__(self, seq: int, offset: int, data: bytes, sums: bytes,
                 last: bool):
        self.seq = seq
        self.offset = offset
        self.data = data
        self.sums = sums
        self.last = last

    def to_frame(self) -> Dict:
        return {"seq": self.seq, "off": self.offset, "data": self.data,
                "sums": self.sums, "last": self.last}


class PipelineError(IOError):
    def __init__(self, msg: str, bad_node: Optional[str] = None):
        super().__init__(msg)
        self.bad_node = bad_node


class DFSClientFaultInjector:
    """Overridable fault points on the client write path (ref:
    hadoop-hdfs-client DFSClientFaultInjector.java — tests subclass the
    singleton to fail the stream at exact packets/acks)."""

    _instance: "DFSClientFaultInjector" = None  # type: ignore[assignment]

    @classmethod
    def get(cls) -> "DFSClientFaultInjector":
        if cls._instance is None:
            cls._instance = DFSClientFaultInjector()
        return cls._instance

    @classmethod
    def set(cls, inst) -> None:
        cls._instance = inst

    # ---- hooks (no-ops by default) ----
    def before_send_packet(self, block: Block, seq: int) -> None: ...
    def on_ack(self, block: Block, seq: int) -> None: ...
    def before_pipeline_setup(self, locations) -> None: ...


class DFSOutputStream:
    def __init__(self, client, path: str, packet_size: int = dt.PACKET_SIZE,
                 chunk_size: int = dt.CHUNK_SIZE,
                 max_packets_in_flight: int = 0,
                 socket_buffer: int = 0):
        self.client = client
        self.path = path
        self.packet_size = packet_size
        # Outstanding-ack window (ref: dfs.client-write-max-packets-in-
        # flight / the reference's dataQueue+ackQueue bound of 80
        # packets): how far the writer may run ahead of the LAST acked
        # packet before blocking. 0 = unbounded (the block-recovery
        # buffer already retains every packet of the open block, so the
        # window bounds DN-side backlog and stall detection, not client
        # memory). ``socket_buffer`` (dfs.client.write.socket.buffer)
        # sizes the per-hop kernel pipe — the depth the wire itself
        # holds; 0 keeps the transport default.
        self.max_packets_in_flight = max_packets_in_flight
        self.socket_buffer = socket_buffer
        self.checksum = DataChecksum(chunk_size)
        self._buf = bytearray()
        self._pos = 0          # bytes written overall
        self._block_pos = 0    # bytes in current block
        self._seq = 0
        self._closed = False
        self._block_size = None  # filled on first allocation
        # Packets of the in-flight block, retained for whole-block recovery.
        self._block_packets: List[_Packet] = []
        self._exclude: Set[str] = set()
        self._current: Optional[Block] = None   # last allocated block
        self._pipeline: Optional[_Pipeline] = None  # open write pipeline

    # --------------------------------------------------------------- writes

    def write(self, data: bytes) -> int:
        if self._closed:
            raise ValueError("stream closed")
        # Zero-copy fast path: packet-sized slices of the caller's buffer
        # go straight out (bulk writers hand ≥1 MB buffers; routing them
        # through the staging bytearray would copy every byte twice).
        if not self._buf and len(data) >= self.packet_size:
            mv = memoryview(data)
            off = 0
            while len(data) - off >= self.packet_size:
                if self._pipeline is None:
                    self._start_block()
                room = self._block_size - self._block_pos
                if room <= 0:
                    self._finish_block()
                    self._start_block()
                    room = self._block_size
                take = min(self.packet_size, len(data) - off, room)
                self._send_packet(bytes(mv[off:off + take]))
                off += take
            if off < len(data):
                self._buf += mv[off:]
            return len(data)
        self._buf += data
        self._drain_full_packets()
        return len(data)

    def _drain_full_packets(self, flush_all: bool = False) -> None:
        while len(self._buf) >= self.packet_size or (flush_all and self._buf):
            if self._pipeline is None:
                self._start_block()  # sets _block_size
            room = self._block_size - self._block_pos
            if room <= 0:
                self._finish_block()
                self._start_block()
                room = self._block_size
            take = min(self.packet_size, len(self._buf), room)
            chunk = bytes(self._buf[:take])
            del self._buf[:take]
            self._send_packet(chunk)

    def _send_packet(self, data: bytes) -> None:
        sums = self.checksum.checksums_for(data)
        pkt = _Packet(self._seq, self._block_pos, data, sums, last=False)
        self._seq += 1
        self._block_packets.append(pkt)
        # account BEFORE streaming: recovery resets _block_pos and replays
        # every retained packet (including this one), so a post-stream
        # increment would double-count the packet that triggered recovery
        self._block_pos += len(data)
        self._pos += len(data)
        self._stream_packet(pkt)

    # ----------------------------------------------------- block lifecycle

    def _start_block(self) -> None:
        """Allocate a block + build its pipeline, excluding known-bad nodes.
        Ref: DataStreamer.nextBlockOutputStream:1656."""
        last_exc: Optional[Exception] = None
        for _ in range(5):
            prev = self._current.to_wire() if self._current else None
            lb = self.client.allocate_block(self.path, prev,
                                            list(self._exclude))
            block, locs = lb.block, lb.locations
            if self._block_size is None:
                self._block_size = self.client.block_size_for(self.path)
            try:
                self._pipeline = _Pipeline(
                    block, locs, self.checksum, token=lb.token,
                    window=self.max_packets_in_flight,
                    socket_buffer=self.socket_buffer)
                self._current = block
                self._block_pos = 0
                self._block_packets = []
                return
            except PipelineError as e:
                last_exc = e
                if e.bad_node:
                    self._exclude.add(e.bad_node)
                self.client.abandon_block(self.path, block)
                log.warning("Pipeline setup for %s failed (%s); retrying",
                            block, e)
        raise IOError(f"could not build pipeline for {self.path}: {last_exc}")

    def _stream_packet(self, pkt: _Packet) -> None:
        try:
            self._pipeline.send(pkt)
        except (OSError, PipelineError) as e:
            self._recover_block(e)

    def _recover_block(self, cause: Exception) -> None:
        """Whole-block recovery: abandon, re-allocate excluding suspects,
        replay retained packets. Recovery is itself recoverable — a DN
        dying mid-replay starts another round with the grown exclude set
        (ref: DataStreamer loops until the cluster is exhausted);
        _start_block raises once no pipeline can be built, which bounds
        the loop."""
        old_packets = list(self._block_packets)
        while True:
            log.warning("Pipeline for %s failed (%s); recovering block",
                        self._current, cause)
            bad = getattr(cause, "bad_node", None)
            if bad:
                self._exclude.add(bad)
            elif self._pipeline is not None:
                self._exclude.update(self._pipeline.suspect_nodes())
            try:
                self._pipeline.close(abort=True)
            except (OSError, RpcError) as e:
                log.debug("pipeline abort-close failed: %s", e)
            self.client.abandon_block(self.path, self._current)
            # The block before the abandoned one was already committed by
            # the add_block(previous=...) that allocated it, so the fresh
            # allocation passes previous=None.
            self._current = None
            self._start_block()
            try:
                for pkt in old_packets:
                    self._block_packets.append(pkt)
                    self._pipeline.send(pkt)
                    self._block_pos += len(pkt.data)
                return
            except (OSError, PipelineError) as e:
                cause = e  # next round excludes the fresh suspects

    def _finish_block(self) -> None:
        """Send the trailing empty packet, await all acks, commit length."""
        if self._pipeline is None:
            return
        last = _Packet(self._seq, self._block_pos, b"", b"", last=True)
        self._seq += 1
        while True:
            try:
                self._pipeline.send(last)
                self._pipeline.wait_all_acked()
                break
            except (OSError, PipelineError) as e:
                self._recover_block(e)
        self._current.num_bytes = self._block_pos
        self._pipeline.close()
        self._pipeline = None
        self._block_packets = []

    # ---------------------------------------------------------------- close

    def flush(self) -> None:
        self._drain_full_packets(flush_all=True)

    def close(self) -> None:
        if self._closed:
            return
        self._drain_full_packets(flush_all=True)
        self._finish_block()  # no-op for an empty file (no pipeline)
        self.client.complete_file(
            self.path, self._current.to_wire() if self._current else None)
        self._closed = True

    def tell(self) -> int:
        return self._pos

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *rest):
        if exc_type is None:
            self.close()
        return False


class _Pipeline:
    """One block's write pipeline: socket to the first DN, ack reader thread.
    Ref: DataStreamer's blockStream + ResponseProcessor."""

    ACK_TIMEOUT_S = 30.0

    def __init__(self, block: Block, locations: List[DatanodeInfo],
                 checksum: DataChecksum, token=None, window: int = 0,
                 socket_buffer: int = 0):
        if not locations:
            raise PipelineError("no locations for block")
        DFSClientFaultInjector.get().before_pipeline_setup(locations)
        self.block = block
        self.locations = locations
        self.window = window            # max unacked packets (0 = no cap)
        self._unacked: "queue.Queue[int]" = queue.Queue()
        self._acked_through = -1
        self._ack_cond = threading.Condition()
        self._error: Optional[Exception] = None
        try:
            self.sock = dt.connect(locations[0].xfer_addr(), timeout=10.0,
                                   buffer_bytes=socket_buffer)
            setup_req = {
                "op": dt.OP_WRITE_BLOCK, "b": block.to_wire(),
                "targets": [t.to_wire() for t in locations[1:]],
                "stage": dt.STAGE_PIPELINE_SETUP_CREATE,
                "bpc": checksum.bytes_per_chunk,
                "tok": token,
            }
            # trace context rides the op header: every DN in the
            # pipeline resumes the CLIENT's span (the forward loop
            # relays the header verbatim), so one trace covers all hops
            ctx = current_context()
            if ctx is not None:
                setup_req["t"] = ctx.to_wire()
            dt.send_frame(self.sock, setup_req)
            setup = dt.recv_frame(self.sock)
            if not setup.get("ok"):
                raise PipelineError(setup.get("em", "pipeline setup failed"),
                                    bad_node=setup.get("bad_node"))
        except (OSError, EOFError) as e:
            raise PipelineError(
                f"connect to {locations[0]} failed: {e}",
                bad_node=locations[0].uuid) from e
        self._ack_thread = threading.Thread(
            target=self._ack_loop, daemon=True,
            name=f"resp-proc-{block.block_id}")
        self._ack_thread.start()

    def _ack_loop(self) -> None:
        try:
            while True:
                ack = dt.recv_frame(self.sock)
                statuses = ack.get("statuses", [])
                if any(s != dt.STATUS_SUCCESS for s in statuses):
                    bad_idx = next(i for i, s in enumerate(statuses)
                                   if s != dt.STATUS_SUCCESS)
                    bad = self.locations[bad_idx].uuid \
                        if bad_idx < len(self.locations) else None
                    raise PipelineError(f"ack failure {statuses}",
                                        bad_node=bad)
                DFSClientFaultInjector.get().on_ack(self.block, ack["seq"])
                with self._ack_cond:
                    self._acked_through = ack["seq"]
                    self._ack_cond.notify_all()
                if ack.get("last"):
                    return
        except (OSError, EOFError, PipelineError, Exception) as e:  # noqa: BLE001
            with self._ack_cond:
                self._error = e if isinstance(e, (OSError, PipelineError)) \
                    else PipelineError(str(e))
                self._ack_cond.notify_all()

    def send(self, pkt: _Packet) -> None:
        DFSClientFaultInjector.get().before_send_packet(self.block, pkt.seq)
        deadline = time.monotonic() + self.ACK_TIMEOUT_S
        with self._ack_cond:
            if self._error is not None:
                raise self._error
            # outstanding-ack window: run at most ``window`` packets
            # ahead of the last ack — deep enough to keep every hop's
            # pipe full, bounded so a wedged DN surfaces as a pipeline
            # error here instead of an unbounded DN-side backlog
            while self.window and pkt.seq - self._acked_through > \
                    self.window:
                if self._error is not None:
                    raise self._error
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise PipelineError(
                        f"ack window ({self.window} packets) stalled "
                        f"for {self.ACK_TIMEOUT_S}s")
                self._ack_cond.wait(remaining)
        self._last_seq = pkt.seq
        dt.send_frame(self.sock, pkt.to_frame())

    def wait_all_acked(self) -> None:
        """Ref: DataStreamer.waitForAckedSeqno:872."""
        deadline = time.monotonic() + self.ACK_TIMEOUT_S
        with self._ack_cond:
            while self._acked_through < getattr(self, "_last_seq", -1):
                if self._error is not None:
                    raise self._error
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise PipelineError("timed out waiting for pipeline acks")
                self._ack_cond.wait(remaining)

    def suspect_nodes(self) -> List[str]:
        return [d.uuid for d in self.locations]

    def close(self, abort: bool = False) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class DFSInputStream:
    def __init__(self, client, path: str, info: Optional[Dict] = None):
        self.client = client
        self.path = path
        if info is None:
            self._refresh_locations()
        else:
            self._set_locations(info)
        self._pos = 0
        self._closed = False
        self._dead: Set[str] = set()
        # ref: dfs.client.read.shortcircuit (the reference defaults it off
        # because domain-socket setup needs operator config; the path-based
        # transport here has no setup, so default on)
        conf = getattr(client, "conf", None)
        self._short_circuit_ok = conf is None or conf.get_bool(
            "dfs.client.read.shortcircuit", True)
        # Hedged reads (ref: DFSInputStream's hedged-read path +
        # dfs.client.hedged.read.threadpool.size/threshold.millis):
        # enabled by a nonzero pool size; after the threshold with no
        # answer from the first replica, a second read races it.
        self._hedged_threshold_s = 0.5
        self._hedged_enabled = False
        from hadoop_tpu.conf.keys import (
            DFS_CLIENT_HEDGED_READ_POOL_SIZE,
            DFS_CLIENT_HEDGED_READ_POOL_SIZE_DEFAULT)
        if conf is not None and conf.get_int(
                DFS_CLIENT_HEDGED_READ_POOL_SIZE,
                DFS_CLIENT_HEDGED_READ_POOL_SIZE_DEFAULT) > 0:
            self._hedged_enabled = True
            self._hedged_threshold_s = conf.get_time_seconds(
                "dfs.client.hedged.read.threshold", 0.5)

    def _refresh_locations(self) -> None:
        self._set_locations(self.client.get_block_locations(self.path))

    def _set_locations(self, info: Dict) -> None:
        self.length = info["length"]
        self.blocks = [LocatedBlock.from_wire(b) for b in info["blocks"]]

    # ---------------------------------------------------------------- reads

    def read(self, n: int = -1) -> bytes:
        if self._closed:
            raise ValueError("stream closed")
        if n < 0:
            n = self.length - self._pos
        out = bytearray()
        while n > 0 and self._pos < self.length:
            chunk = self._read_some(self._pos, n)
            if not chunk:
                break
            out += chunk
            self._pos += len(chunk)
            n -= len(chunk)
        return bytes(out)

    def pread(self, position: int, length: int) -> bytes:
        """Positioned read, does not move the cursor.
        Ref: DFSInputStream.read(long,...) / PositionedReadable."""
        out = bytearray()
        pos = position
        remaining = min(length, self.length - position)
        while remaining > 0:
            chunk = self._fetch_range(pos, remaining)
            if not chunk:
                break
            out += chunk
            pos += len(chunk)
            remaining -= len(chunk)
        return bytes(out)

    def seek(self, pos: int) -> None:
        self._pos = pos

    def tell(self) -> int:
        return self._pos

    def _block_for(self, pos: int) -> LocatedBlock:
        for lb in self.blocks:
            if lb.offset <= pos < lb.offset + lb.block.num_bytes:
                return lb
        raise EOFError(f"offset {pos} beyond file length {self.length}")

    def _read_some(self, pos: int, want: int) -> bytes:
        return self._fetch_range(pos, want)

    # Refresh/backoff rounds when every replica fails or the NN reports
    # no locations (nodes transiently dead under load, re-replication in
    # flight, a fresh post-failover active still collecting block
    # reports — report interval is seconds). Ref: DFSInputStream
    # chooseDataNode's retry window (dfs.client.retries.window.base —
    # sleeps then refetches locations). The window must outlast one
    # block-report interval: 0.5+1+1.5+2+2.5 = 7.5s of backoff.
    LOCATION_RETRIES = 6
    RETRY_BACKOFF_S = 0.5

    def _fetch_range(self, pos: int, want: int) -> bytes:
        """Read up to ``want`` bytes at pos from one replica, with failover.
        Ref: DFSInputStream.blockSeekTo:639 + read retry loop.

        Wrapped in a ``dfs.client.read`` span — the ROOT of a read
        trace when no span is active (the htrace model: the client
        decides sampling; NN handler + DN xceiver spans join it over
        the wire)."""
        with global_tracer().span("dfs.client.read") as rsp:
            rsp.add_kv("path", self.path)
            rsp.add_kv("pos", str(pos))
            return self._fetch_range_traced(pos, want)

    def _fetch_range_traced(self, pos: int, want: int) -> bytes:
        lb = self._block_for(pos)
        in_block_off = pos - lb.offset
        want = min(want, lb.block.num_bytes - in_block_off)
        errors: List[str] = []
        candidates = [d for d in lb.locations if d.uuid not in self._dead] \
            or lb.locations  # all dead? retry everyone once
        if self._hedged_enabled and len(candidates) > 1:
            try:
                return self._hedged_fetch(candidates, lb.block,
                                          in_block_off, want)
            except (OSError, EOFError, IOError) as e:
                errors.append(f"hedged: {e}")
                # Every candidate was already tried (and failed) inside
                # the hedge — go straight to the refresh/backoff rounds
                # instead of paying each connect timeout a second time.
                candidates = []
        for dn in candidates:
            try:
                return self._read_from_datanode(dn, lb.block, in_block_off,
                                                want)
            except ChecksumError:
                log.warning("Checksum error reading %s from %s; reporting",
                            lb.block, dn)
                self.client.report_bad_block(lb.block, dn.uuid)
                self._dead.add(dn.uuid)
                errors.append(f"{dn}: checksum")
            except (OSError, EOFError, IOError) as e:
                self._dead.add(dn.uuid)
                errors.append(f"{dn}: {e}")
        # Refresh + backoff rounds: replicas may have moved
        # (re-replication) or their nodes may be only transiently dead.
        for attempt in range(self.LOCATION_RETRIES):
            self._refresh_locations()
            self._dead.clear()
            lb = self._block_for(pos)
            for dn in lb.locations:
                try:
                    return self._read_from_datanode(dn, lb.block,
                                                    in_block_off, want)
                except ChecksumError:
                    # report in the retry rounds too — swallowing it in
                    # the generic handler meant the NN never learned of
                    # the corruption (no re-replication) and, with
                    # _dead cleared each round, the client re-downloaded
                    # the same corrupt replica every round
                    log.warning("Checksum error reading %s from %s; "
                                "reporting", lb.block, dn)
                    self.client.report_bad_block(lb.block, dn.uuid)
                    self._dead.add(dn.uuid)
                    errors.append(f"{dn}: checksum")
                except (OSError, EOFError, IOError) as e:
                    errors.append(f"{dn}: {e}")
            if attempt < self.LOCATION_RETRIES - 1:
                # exponential + jittered: a fleet of readers chasing the
                # same re-replicating block must not stampede the NN in
                # lockstep rounds (ref: RetryPolicies.exponentialBackoff)
                time.sleep(backoff_delay(self.RETRY_BACKOFF_S, attempt,
                                         max_s=8.0))
        raise IOError(f"could not read {self.path} at {pos} from any "
                      f"replica: {errors}")

    def _hedged_fetch(self, candidates: List[DatanodeInfo], block: Block,
                      offset: int, want: int) -> bytes:
        """Race replicas: the first read gets ``threshold`` alone; then a
        hedge starts on the next replica and the first success wins. A
        replica that errors triggers the next hedge immediately. Losers
        run to completion in the pool (ref: DFSInputStream
        .hedgedFetchBlockByteRange — it too lets stragglers finish)."""
        import concurrent.futures as cf
        pending = list(candidates)
        by_future = {}
        first = pending.pop(0)
        fut = self.client.hedged_submit(self._read_from_datanode, first,
                                        block, offset, want)
        if fut is None:
            # Pool saturated by straggling losers: read sequentially
            # rather than queueing behind them.
            return self._read_from_datanode(first, block, offset, want)
        by_future[fut] = first
        errors: List[str] = []
        while True:
            timeout = self._hedged_threshold_s if pending else None
            done, _ = cf.wait(list(by_future), timeout=timeout,
                              return_when=cf.FIRST_COMPLETED)
            for f in done:
                dn = by_future.pop(f)
                exc = f.exception()
                if exc is None:
                    self.client.hedged_wins += 1
                    return f.result()
                # Same failure bookkeeping as the sequential path: a
                # corrupt replica is reported and a failed one goes on
                # the dead list so later reads skip it.
                if isinstance(exc, ChecksumError):
                    log.warning("Checksum error (hedged) reading %s from"
                                " %s; reporting", block, dn)
                    self.client.report_bad_block(block, dn.uuid)
                self._dead.add(dn.uuid)
                errors.append(f"{dn}: {exc}")
            if pending:
                nxt = pending.pop(0)
                fut = self.client.hedged_submit(self._read_from_datanode,
                                                nxt, block, offset, want)
                if fut is None:
                    if by_future:
                        pending.insert(0, nxt)  # retry hedging next wake
                        continue
                    return self._read_from_datanode(nxt, block, offset,
                                                    want)
                self.client.hedged_reads += 1
                by_future[fut] = nxt
            elif not by_future:
                raise IOError(f"all hedged reads failed: {errors}")

    def _token_for(self, block: Block):
        from hadoop_tpu.io import erasurecode as ecmod
        bid = block.block_id
        gid = ecmod.group_id_of(bid) if ecmod.is_striped_id(bid) else bid
        for lb in self.blocks:
            if lb.block.block_id in (bid, gid):
                return lb.token
        return None

    def _read_from_datanode(self, dn: DatanodeInfo, block: Block,
                            offset: int, want: int) -> bytes:
        """BlockReaderFactory seam (ref: BlockReaderFactory.java:354-381):
        local replica → short-circuit direct file read; else TCP."""
        if self._short_circuit_ok:
            from hadoop_tpu.dfs.client.shortcircuit import (
                ShortCircuitCache, ShortCircuitUnavailable)
            cache = ShortCircuitCache.get()
            if cache.is_local(dn):
                try:
                    return cache.read(
                        dn, block, offset, want,
                        token=self._token_for(block),
                        socket_template=self.client.conf.get(
                            "dfs.domain.socket.path", ""))
                except ShortCircuitUnavailable as e:
                    log.debug("short-circuit read of %s fell back: %s",
                              block, e)
        return self._read_remote(dn, block, offset, want)

    def _read_remote(self, dn: DatanodeInfo, block: Block,
                     offset: int, want: int) -> bytes:
        sock = dt.connect(dn.xfer_addr(), timeout=10.0)
        try:
            req = {"op": dt.OP_READ_BLOCK, "b": block.to_wire(),
                   "tok": self._token_for(block),
                   "offset": offset, "length": want}
            ctx = current_context()
            if ctx is not None:
                req["t"] = ctx.to_wire()
            dt.send_frame(sock, req)
            setup = dt.recv_frame(sock)
            if not setup.get("ok"):
                raise IOError(setup.get("em", "read setup failed"))
            # verify with the replica's stored chunking, not our default
            checksum = DataChecksum(dt.checked_bpc(setup))
            out = bytearray()
            skip = None
            while True:
                pkt = dt.recv_frame(sock)
                if pkt.get("last"):
                    break
                data, sums = pkt["data"], pkt["sums"]
                checksum.verify(data, sums, base_pos=pkt["off"])
                if skip is None:
                    skip = offset - pkt["off"]  # chunk alignment slack
                take = data[skip:skip + (want - len(out))] if skip else \
                    data[:want - len(out)]
                out += take
                skip = 0
            return bytes(out)
        finally:
            sock.close()

    def close(self) -> None:
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
