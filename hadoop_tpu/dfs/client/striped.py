"""Striped (erasure-coded) client streams.

Write path parity (ref: hadoop-hdfs-client DFSStripedOutputStream.java,
StripedDataStreamer.java): the stream buffers one stripe row (k cells);
when full it computes the m parity cells and sends cell-sized packets to
the k+m unit writers — each unit is a plain single-node block write (no
mirror pipeline: the parity IS the redundancy). Up to m unit writers may
fail mid-group; the group still completes and the NameNode schedules
background reconstruction of the lost units.

Read path parity (ref: DFSStripedInputStream.java, StripeReader.java):
logical offsets map to (stripe, cell-column); reads go straight to the
data units, and a missing/corrupt unit triggers a decode read — fetch
the stripe's cells from any k live units (data or parity) and rebuild
the missing cell with the policy's raw coder.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Set

from hadoop_tpu.dfs.protocol import datatransfer as dt
from hadoop_tpu.dfs.protocol.records import Block, DatanodeInfo, LocatedBlock
from hadoop_tpu.io import erasurecode as ec
from hadoop_tpu.util.crc import ChecksumError, DataChecksum

log = logging.getLogger(__name__)


class _UnitWriter:
    """One storage unit's write connection: a single-target block write.
    Ref: StripedDataStreamer — a DataStreamer with no mirror chain.

    The terminal DN acks every packet inline in its receive loop, so acks
    must be consumed as the write progresses — ``send_cell`` drains any
    already-arrived acks non-blockingly; letting them pile up until
    ``finish`` would eventually fill both socket buffers and deadlock the
    DN mid-block on large units."""

    def __init__(self, unit_block: Block, target: DatanodeInfo,
                 checksum: DataChecksum, token=None):
        self.block = unit_block
        self.target = target
        self.checksum = checksum
        self.seq = 0
        self.pos = 0
        self.sock = dt.connect(target.xfer_addr(), timeout=10.0)
        dt.send_frame(self.sock, {
            "op": dt.OP_WRITE_BLOCK, "b": unit_block.to_wire(),
            "targets": [], "stage": dt.STAGE_PIPELINE_SETUP_CREATE,
            "bpc": checksum.bytes_per_chunk, "tok": token,
        })
        setup = dt.recv_frame(self.sock)
        if not setup.get("ok"):
            raise IOError(setup.get("em", "unit writer setup failed"))

    def _check_ack(self, ack: Dict) -> None:
        if any(s != dt.STATUS_SUCCESS for s in ack.get("statuses", [])):
            raise IOError(f"unit write ack failure: {ack}")

    def _drain_ready_acks(self) -> None:
        import select
        while select.select([self.sock], [], [], 0)[0]:
            self._check_ack(dt.recv_frame(self.sock))

    def send_cell(self, data: bytes) -> None:
        self._drain_ready_acks()
        sums = self.checksum.checksums_for(data)
        dt.send_frame(self.sock, {"seq": self.seq, "off": self.pos,
                                  "data": data, "sums": sums, "last": False})
        self.seq += 1
        self.pos += len(data)

    def finish(self) -> None:
        """Send trailing packet, block until the last ack arrives."""
        dt.send_frame(self.sock, {"seq": self.seq, "off": self.pos,
                                  "data": b"", "sums": b"", "last": True})
        while True:
            ack = dt.recv_frame(self.sock)
            self._check_ack(ack)
            if ack.get("last"):
                return

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class DFSStripedOutputStream:
    """Ref: DFSStripedOutputStream.java. Created by DFSClient.create for
    paths under an EC-policy directory."""

    def __init__(self, client, path: str, policy_name: str):
        self.client = client
        self.path = path
        self.policy = ec.get_policy(policy_name)
        self.coder = self.policy.new_coder()
        self.checksum = DataChecksum(dt.CHUNK_SIZE)
        self._buf = bytearray()      # pending bytes of the current stripe row
        self._pos = 0                # logical bytes written
        self._group_pos = 0          # logical bytes in the current group
        self._group_size = None      # k * block_size (logical bytes/group)
        self._current: Optional[Block] = None
        self._writers: List[Optional[_UnitWriter]] = []
        self._closed = False

    # --------------------------------------------------------------- writes

    def write(self, data: bytes) -> int:
        if self._closed:
            raise ValueError("stream closed")
        stripe_bytes = self.policy.k * self.policy.cell_size
        self._buf += data
        while len(self._buf) >= stripe_bytes:
            row = bytes(self._buf[:stripe_bytes])
            del self._buf[:stripe_bytes]
            self._write_stripe(row)
        return len(data)

    def _write_stripe(self, row: bytes) -> None:
        if self._writers == []:
            self._start_group()
        cell = self.policy.cell_size
        k = self.policy.k
        data_cells = [row[i * cell:(i + 1) * cell] for i in range(k)]
        if len(data_cells[0]) == 0:
            return
        padded = ec.pad_stripe_cells(data_cells)
        parity = self.coder.encode(padded)
        for i, w in enumerate(self._writers):
            if w is None:
                continue
            # Parity cells go out at stripe width; data cells carry only
            # real bytes (an empty tail cell sends nothing).
            payload = data_cells[i] if i < k else parity[i - k]
            if not payload:
                continue
            try:
                w.send_cell(payload)
            except (OSError, IOError) as e:
                log.warning("unit %d writer for %s failed: %s", i,
                            self._current, e)
                w.close()
                self._writers[i] = None
        if sum(1 for w in self._writers if w is not None) < k:
            raise IOError(
                f"too many failed unit writers for {self.path} "
                f"(<{k} of {self.policy.num_units} healthy)")
        self._group_pos += len(row)
        self._pos += len(row)
        if self._group_pos >= self._group_size:
            self._finish_group()

    # ----------------------------------------------------- group lifecycle

    def _start_group(self) -> None:
        lb = self.client.allocate_block(self.path,
                                        self._current.to_wire()
                                        if self._current else None, [])
        if self._group_size is None:
            self._group_size = (self.client.block_size_for(self.path)
                                * self.policy.k)
        by_index: Dict[int, DatanodeInfo] = {}
        for loc, idx in zip(lb.locations, lb.indices or []):
            by_index[idx] = loc
        self._writers = []
        for i in range(self.policy.num_units):
            target = by_index.get(i)
            if target is None:
                self._writers.append(None)
                continue
            unit = Block(lb.block.block_id + i, lb.block.gen_stamp, 0)
            try:
                self._writers.append(
                    _UnitWriter(unit, target, self.checksum,
                                token=lb.token))
            except (OSError, IOError) as e:
                log.warning("unit %d writer setup failed: %s", i, e)
                self._writers.append(None)
        healthy = sum(1 for w in self._writers if w is not None)
        if healthy < self.policy.k:
            raise IOError(f"cannot open ≥{self.policy.k} unit writers "
                          f"({healthy} healthy)")
        self._current = lb.block
        self._group_pos = 0

    def _finish_group(self) -> None:
        if not self._writers:
            return
        for i, w in enumerate(self._writers):
            if w is None:
                continue
            try:
                w.finish()
            except (OSError, IOError) as e:
                log.warning("unit %d finish failed: %s", i, e)
                self._writers[i] = None
            finally:
                w.close()
        if sum(1 for w in self._writers if w is not None) < self.policy.k:
            raise IOError(f"group {self._current} lost >m units at close")
        self._current.num_bytes = self._group_pos
        self._writers = []

    # ---------------------------------------------------------------- close

    def flush(self) -> None:
        pass  # stripes flush on row boundaries; close() drains the tail

    def close(self) -> None:
        if self._closed:
            return
        stripe_bytes = self.policy.k * self.policy.cell_size
        while self._buf:
            row = bytes(self._buf[:stripe_bytes])
            del self._buf[:stripe_bytes]
            self._write_stripe(row)
        self._finish_group()
        self.client.complete_file(
            self.path, self._current.to_wire() if self._current else None)
        self._closed = True

    def tell(self) -> int:
        return self._pos

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *rest):
        if exc_type is None:
            self.close()
        return False


class DFSStripedInputStream:
    """Ref: DFSStripedInputStream.java + StripeReader.java."""

    def __init__(self, client, path: str, info: Optional[Dict] = None):
        self.client = client
        self.path = path
        self._pos = 0
        self._closed = False
        self._dead: Set[str] = set()
        if info is None:
            self._refresh_locations()
        else:
            self._set_locations(info)

    def _refresh_locations(self) -> None:
        self._set_locations(self.client.get_block_locations(self.path))

    def _set_locations(self, info: Dict) -> None:
        self.length = info["length"]
        self.blocks = [LocatedBlock.from_wire(b) for b in info["blocks"]]

    # ---------------------------------------------------------------- reads

    def read(self, n: int = -1) -> bytes:
        if self._closed:
            raise ValueError("stream closed")
        if n < 0:
            n = self.length - self._pos
        out = bytearray()
        while n > 0 and self._pos < self.length:
            chunk = self._fetch(self._pos, n)
            if not chunk:
                break
            out += chunk
            self._pos += len(chunk)
            n -= len(chunk)
        return bytes(out)

    def pread(self, position: int, length: int) -> bytes:
        out = bytearray()
        pos = position
        remaining = min(length, self.length - position)
        while remaining > 0:
            chunk = self._fetch(pos, remaining)
            if not chunk:
                break
            out += chunk
            pos += len(chunk)
            remaining -= len(chunk)
        return bytes(out)

    def seek(self, pos: int) -> None:
        self._pos = pos

    def tell(self) -> int:
        return self._pos

    def _group_for(self, pos: int) -> LocatedBlock:
        for lb in self.blocks:
            if lb.offset <= pos < lb.offset + lb.block.num_bytes:
                return lb
        raise EOFError(f"offset {pos} beyond file length {self.length}")

    def _token_for(self, block: Block):
        """Unit block → its GROUP's access token (the NN mints one per
        group; see xceiver's striped-id token resolution)."""
        from hadoop_tpu.io import erasurecode as ecmod
        bid = block.block_id
        gid = ecmod.group_id_of(bid) if ecmod.is_striped_id(bid) else bid
        for lb in self.blocks:
            if lb.block.block_id in (bid, gid):
                return lb.token
        return None

    def _fetch(self, pos: int, want: int) -> bytes:
        """Read up to ``want`` bytes at ``pos``, capped to one cell."""
        lb = self._group_for(pos)
        policy = ec.get_policy(lb.ec_policy)
        cell = policy.cell_size
        k = policy.k
        gpos = pos - lb.offset                  # logical offset in group
        stripe, in_stripe = divmod(gpos, k * cell)
        col, in_cell = divmod(in_stripe, cell)
        # Bytes remaining in this cell and in the group:
        take = min(want, cell - in_cell, lb.block.num_bytes - gpos)
        unit_off = stripe * cell + in_cell      # offset within unit `col`
        loc = self._unit_location(lb, col)
        if loc is not None and loc.uuid not in self._dead:
            try:
                return self._read_unit(loc, lb, policy, col, unit_off, take)
            except ChecksumError:
                unit = Block(lb.block.block_id + col, lb.block.gen_stamp)
                self.client.report_bad_block(unit, loc.uuid)
                self._dead.add(loc.uuid)
            except (OSError, EOFError, IOError) as e:
                log.debug("unit %d read failed (%s); decoding", col, e)
                self._dead.add(loc.uuid)
        return self._decode_fetch(lb, policy, stripe, col, in_cell, take)

    def _unit_location(self, lb: LocatedBlock,
                       idx: int) -> Optional[DatanodeInfo]:
        for loc, i in zip(lb.locations, lb.indices or []):
            if i == idx:
                return loc
        return None

    def _read_unit(self, loc: DatanodeInfo, lb: LocatedBlock,
                   policy: ec.ECPolicy, idx: int, offset: int,
                   length: int) -> bytes:
        unit_len = ec.unit_length(lb.block.num_bytes, policy, idx)
        unit = Block(lb.block.block_id + idx, lb.block.gen_stamp, unit_len)
        return dt.read_block_range(loc.xfer_addr(), unit.to_wire(), offset,
                                   min(length, unit_len - offset),
                                   token=self._token_for(unit))

    def _decode_fetch(self, lb: LocatedBlock, policy: ec.ECPolicy,
                      stripe: int, col: int, in_cell: int,
                      take: int) -> bytes:
        """Rebuild the wanted cell from any k live units of its stripe.
        Ref: StripeReader.readStripe + decode."""
        cell = policy.cell_size
        k = policy.k
        # Cell lengths within this stripe (possibly the partial last one).
        group_len = lb.block.num_bytes
        cells_len = [
            max(0, min(group_len - (stripe * k + i) * cell, cell))
            for i in range(k)]
        width = max(cells_len) if cells_len else 0
        if width == 0:
            return b""
        shards: List[Optional[bytes]] = [None] * policy.num_units
        got = 0
        errors: List[str] = []
        for idx in range(policy.num_units):
            if got >= k:
                break
            if idx == col:
                continue
            loc = self._unit_location(lb, idx)
            if loc is None or loc.uuid in self._dead:
                continue
            want_len = cells_len[idx] if idx < k else width
            try:
                raw = self._read_unit(loc, lb, policy, idx,
                                      stripe * cell, want_len)
                if len(raw) < width:
                    raw = raw + b"\0" * (width - len(raw))
                shards[idx] = raw
                got += 1
            except (OSError, EOFError, IOError, ChecksumError) as e:
                errors.append(f"unit {idx}: {e}")
        if got < k:
            raise IOError(
                f"cannot decode {self.path} stripe {stripe}: only {got} "
                f"of >={k} units readable; errors: {errors}")
        full = policy.new_coder().decode(shards)
        data = full[col][:cells_len[col]] if col < k else full[col]
        return data[in_cell:in_cell + take]

    def close(self) -> None:
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
