from hadoop_tpu.dfs.datanode.datanode import DataNode

__all__ = ["DataNode"]
