"""On-disk replica store.

Parity with the reference's dataset layer (ref:
server/datanode/fsdataset/impl/FsDatasetImpl.java:136, ReplicaInfo state
machine, BlockMetadataHeader): replicas live as a data file + a ``.meta``
side file (DataChecksum header + one CRC per chunk). Under-construction
replicas ("rbw" — replica being written) live in ``rbw/`` and move to
``finalized/`` atomically on completion.

Layout:  <dir>/rbw/blk_<id>            + blk_<id>.meta
         <dir>/finalized/blk_<id>      + blk_<id>.meta
(The gen stamp is recorded inside the meta header trailer, not the filename,
so recovery-time stamp bumps are a metadata rewrite, not a data copy.)
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.dfs.protocol.records import Block, ReplicaNotFoundError
from hadoop_tpu.util.crc import DataChecksum

_META_MAGIC = b"HTPM"


class Replica:
    FINALIZED = "finalized"
    RBW = "rbw"

    __slots__ = ("block_id", "gen_stamp", "num_bytes", "state")

    def __init__(self, block_id: int, gen_stamp: int, num_bytes: int,
                 state: str):
        self.block_id = block_id
        self.gen_stamp = gen_stamp
        self.num_bytes = num_bytes
        self.state = state

    def to_block(self) -> Block:
        return Block(self.block_id, self.gen_stamp, self.num_bytes)


class _OpenReplica:
    """An rbw replica with open file handles, fed packet by packet.

    Block recovery may *steal* an open writer (ref: ReplicaInPipeline
    .stopWriter): the store flushes + closes the handles under ``_io_lock``
    and marks the writer stolen; the feeding xceiver's next write raises and
    its teardown becomes a no-op, so recovery never races buffered data or
    moves files out from under live handles."""

    def __init__(self, store: "BlockStore", block: Block, checksum: DataChecksum):
        self.store = store
        self.block_id = block.block_id
        self.gen_stamp = block.gen_stamp
        self.checksum = checksum
        self.data_path = store._path(Replica.RBW, block.block_id)
        self.meta_path = self.data_path + ".meta"
        self._data_f = open(self.data_path, "wb")
        self._meta_f = open(self.meta_path, "wb")
        self._meta_f.write(_META_MAGIC + struct.pack(">q", block.gen_stamp)
                           + checksum.header())
        self.num_bytes = 0
        self.stolen = False
        # Bytes of the current incomplete trailing chunk. A client hflush
        # can end a packet mid-chunk (ref: BlockReceiver's partial-chunk
        # handling); the next packet then starts unaligned, so its
        # packet-relative sums can't be appended verbatim — the straddling
        # chunk's CRC is recomputed over (partial + new) instead.
        self._partial = b""
        # NativeIO drop-behind-writes discipline (ref: BlockReceiver's
        # manageWriterOsCache under dfs.datanode.drop.cache.behind.writes
        # + sync.behind.writes, both OFF by default like the reference —
        # right for archival/streaming ingest, wrong for write-then-read
        # workloads like shuffle spills): kick writeback for the newest
        # window, evict only the PREVIOUS (already-synced) one so
        # DONTNEED hits clean pages.
        self._drop_behind = getattr(store, "drop_behind_writes", False)
        self._synced_to = 0
        self._dropped_to = 0
        self._io_lock = threading.Lock()

    DROP_BEHIND_BYTES = 8 * 1024 * 1024

    def write_packet(self, data: bytes, sums: bytes) -> None:
        with self._io_lock:
            if self.stolen:
                raise IOError(f"writer of blk_{self.block_id} stopped by "
                              f"block recovery")
            self._data_f.write(data)
            if self._drop_behind and \
                    self.num_bytes - self._synced_to >= \
                    self.DROP_BEHIND_BYTES:
                from hadoop_tpu import native
                upto = self.num_bytes - (self.num_bytes %
                                         self.DROP_BEHIND_BYTES)
                self._data_f.flush()
                fd = self._data_f.fileno()
                native.sync_file_range(fd, self._synced_to,
                                       upto - self._synced_to)
                # The range synced LAST window has completed writeback
                # by now — those pages evict; the fresh window waits.
                if self._synced_to > self._dropped_to:
                    native.fadvise(fd, self._dropped_to,
                                   self._synced_to - self._dropped_to,
                                   native.FADV_DONTNEED)
                    self._dropped_to = self._synced_to
                self._synced_to = upto
            bpc = self.checksum.bytes_per_chunk
            if self._partial:
                # Rewind the partial chunk's provisional CRC and re-cover
                # it together with the new bytes, chunk-aligned.
                self._meta_f.seek(-4, os.SEEK_END)
                self._meta_f.truncate()
                combined = self._partial + data
                self._meta_f.write(self.checksum.checksums_for(combined))
            else:
                combined = data
                self._meta_f.write(sums)
            self._partial = combined[len(combined) // bpc * bpc:]
            self.num_bytes += len(data)

    def fsync(self) -> None:
        with self._io_lock:
            if self.stolen:
                return
            self._fsync_locked()

    def _fsync_locked(self) -> None:
        self._data_f.flush()
        os.fsync(self._data_f.fileno())
        self._meta_f.flush()
        os.fsync(self._meta_f.fileno())

    def close(self) -> None:
        with self._io_lock:
            if self.stolen:
                return
            self._close_locked()
        self.store._writer_closed(self)

    def _close_locked(self) -> None:
        self._data_f.close()
        self._meta_f.close()

    def steal(self) -> None:
        """Flush + close + fence the writer (recovery path)."""
        with self._io_lock:
            if self.stolen:
                return
            try:
                self._fsync_locked()
            finally:
                self._close_locked()
                self.stolen = True
        self.store._writer_closed(self)

    def abort(self) -> None:
        with self._io_lock:
            if self.stolen:
                return  # recovery owns the files now
            self._close_locked()
            self.stolen = True
        self.store._writer_closed(self)
        for p in (self.data_path, self.meta_path):
            if os.path.exists(p):
                os.remove(p)


class BlockStore:
    def __init__(self, directory: str, chunk_size: int = 512,
                 capacity_override: int = 0, sync_on_close: bool = False,
                 drop_behind_writes: bool = False):
        self.dir = directory
        self.chunk_size = chunk_size
        # ref: dfs.datanode.drop.cache.behind.writes (NativeIO page-cache
        # discipline; off by default like the reference)
        self.drop_behind_writes = drop_behind_writes
        # fsync on finalize — ref: dfs.datanode.synconclose, FALSE in the
        # reference too (DataNode.java / BlockReceiver close path): block
        # durability comes from 3-way replication, not per-block fsync;
        # fsync per finalize costs ~3x write throughput on ext4.
        self.sync_on_close = sync_on_close
        # Centralized-cache pinning (ref: fsdataset/impl/FsDatasetCache
        # .java — mmap+mlock there; resident bytes here): block id →
        # in-memory copy served by read_chunks without touching disk.
        self._cached: Dict[int, bytes] = {}
        self.max_cache_bytes = 64 * 1024 * 1024
        # Advertised capacity for shared volumes / simulated heterogeneity
        # (ref: dfs.datanode.du.reserved + SimulatedFSDataset's capacity).
        self.capacity_override = capacity_override
        for sub in (Replica.RBW, Replica.FINALIZED):
            os.makedirs(os.path.join(directory, sub), exist_ok=True)
        self._replicas: Dict[int, Replica] = {}
        self._open_writers: Dict[int, _OpenReplica] = {}
        self._lock = threading.Lock()
        self._scan()

    def _path(self, state: str, block_id: int) -> str:
        return os.path.join(self.dir, state, f"blk_{block_id}")

    def _scan(self) -> None:
        """Startup inventory (ref: DataNode's DirectoryScanner.java:64 initial
        pass). rbw replicas left by a crash are kept — the NN decides their
        fate via block recovery or invalidation."""
        for state in (Replica.FINALIZED, Replica.RBW):
            d = os.path.join(self.dir, state)
            for name in os.listdir(d):
                if not name.startswith("blk_") or name.endswith(".meta"):
                    continue
                bid = int(name[4:])
                data_path = os.path.join(d, name)
                gs = self._read_meta_genstamp(data_path + ".meta")
                if gs is None:
                    continue
                self._replicas[bid] = Replica(
                    bid, gs, os.path.getsize(data_path), state)

    @staticmethod
    def _read_meta_genstamp(meta_path: str) -> Optional[int]:
        try:
            with open(meta_path, "rb") as f:
                magic = f.read(4)
                if magic != _META_MAGIC:
                    return None
                return struct.unpack(">q", f.read(8))[0]
        except OSError:
            return None

    # --------------------------------------------------------------- writes

    def create_rbw(self, block: Block, checksum: DataChecksum) -> _OpenReplica:
        # Claim loop: the replace decision and the claim must be ONE
        # atomic step, or two concurrent setups for the same block both
        # pass the stale-check and the loser deletes the winner's open
        # files unfenced (the winner's later finalize would then publish
        # the loser's partial data). steal() must run OUTSIDE the lock —
        # it re-enters via _writer_closed.
        while True:
            with self._lock:
                stale_writer = self._open_writers.get(block.block_id)
                if stale_writer is None:
                    existing = self._replicas.get(block.block_id)
                    if existing is not None:
                        if existing.state == Replica.FINALIZED:
                            raise IOError(
                                f"block {block.block_id} already finalized")
                        # Pipeline recovery overwrites a stale rbw replica.
                        self._remove_files(existing)
                        del self._replicas[block.block_id]
                    rep = Replica(block.block_id, block.gen_stamp, 0,
                                  Replica.RBW)
                    self._replicas[block.block_id] = rep
                    writer = _OpenReplica(self, block, checksum)
                    self._open_writers[block.block_id] = writer
                    return writer
            stale_writer.steal()  # fence, then retry the claim

    def _writer_closed(self, writer: "_OpenReplica") -> None:
        with self._lock:
            if self._open_writers.get(writer.block_id) is writer:
                del self._open_writers[writer.block_id]

    def finalize(self, open_rep: _OpenReplica) -> Replica:
        """flush (+ optional fsync) + atomic move rbw → finalized.
        Ref: FsDatasetImpl.finalizeBlock."""
        if self.sync_on_close:
            open_rep.fsync()
        open_rep.close()
        dst = self._path(Replica.FINALIZED, open_rep.block_id)
        os.replace(open_rep.data_path, dst)
        os.replace(open_rep.meta_path, dst + ".meta")
        with self._lock:
            rep = Replica(open_rep.block_id, open_rep.gen_stamp,
                          open_rep.num_bytes, Replica.FINALIZED)
            self._replicas[open_rep.block_id] = rep
            return rep

    def invalidate(self, block: Block) -> bool:
        """Delete a replica. Ref: FsDatasetImpl.invalidate."""
        with self._lock:
            rep = self._replicas.pop(block.block_id, None)
            if rep is None:
                return False
            self._remove_files(rep)
            return True

    def _remove_files(self, rep: Replica) -> None:
        p = self._path(rep.state, rep.block_id)
        for path in (p, p + ".meta"):
            if os.path.exists(path):
                os.remove(path)

    def _reconcile_rbw_files(self, data_path: str, meta_path: str) -> int:
        """Crash alignment before promoting an rbw: the data and meta
        files flush independently, so after a DN crash one can be ahead
        of the other. Truncate both to the longest prefix whose stored
        checksums actually verify — finalizing at the raw data size
        would mint a replica whose tail fails every future read and
        gets invalidated, destroying the recoverable prefix (ref:
        FsDatasetImpl.recoverRbw's checksum/length alignment +
        truncateBlock)."""
        hdr = 12 + DataChecksum.HEADER_LEN
        try:
            with open(meta_path, "rb") as f:
                f.seek(12)
                checksum = DataChecksum.from_header(
                    f.read(DataChecksum.HEADER_LEN))
        except (OSError, ValueError, struct.error):
            return 0  # torn meta header: nothing is verifiable
        bpc = checksum.bytes_per_chunk
        dsize = os.path.getsize(data_path)
        n_sums = max(0, os.path.getsize(meta_path) - hdr) // 4
        length = min(dsize, n_sums * bpc)
        with open(data_path, "rb") as df, open(meta_path, "rb") as mf:
            while length > 0:
                last = (length - 1) // bpc
                start = last * bpc
                df.seek(start)
                chunk = df.read(length - start)
                mf.seek(hdr + last * 4)
                stored = mf.read(4)
                if len(stored) == 4 and \
                        checksum.checksums_for(chunk) == stored:
                    break
                length = start  # drop the unverifiable tail chunk
        n_keep = (length + bpc - 1) // bpc
        if length < dsize:
            with open(data_path, "r+b") as f:
                f.truncate(length)
        if hdr + n_keep * 4 < hdr + n_sums * 4:
            with open(meta_path, "r+b") as f:
                f.truncate(hdr + n_keep * 4)
        return length

    def finalize_existing(self, block_id: int) -> Optional[Replica]:
        """Block recovery: promote an rbw replica to finalized at its current
        length. Stops a still-open writer first so buffered bytes reach disk
        and the handles can't race the rename.
        Ref: FsDatasetImpl.recoverRbw (stopWriter) + finalizeBlock."""
        with self._lock:
            writer = self._open_writers.get(block_id)
        if writer is not None:
            writer.steal()
        with self._lock:
            rep = self._replicas.get(block_id)
            if rep is None:
                raise ReplicaNotFoundError(str(block_id))
            if rep.state == Replica.FINALIZED:
                return rep
            src = self._path(Replica.RBW, block_id)
            dst = self._path(Replica.FINALIZED, block_id)
            # The verified on-disk prefix is the truth: an interrupted
            # pipeline leaves the in-memory record at 0 while the rbw
            # file holds the data (and a crash can tear the tail).
            rep.num_bytes = self._reconcile_rbw_files(src, src + ".meta")
            os.replace(src, dst)
            os.replace(src + ".meta", dst + ".meta")
            rep.state = Replica.FINALIZED
            return rep

    def update_gen_stamp(self, block_id: int, new_gs: int) -> None:
        """Block recovery: bump the stamp in place (metadata rewrite)."""
        with self._lock:
            rep = self._replicas.get(block_id)
            if rep is None:
                raise ReplicaNotFoundError(str(block_id))
            meta = self._path(rep.state, block_id) + ".meta"
            with open(meta, "r+b") as f:
                f.seek(4)
                f.write(struct.pack(">q", new_gs))
            rep.gen_stamp = new_gs

    # ---------------------------------------------------------------- reads

    def get_replica(self, block_id: int) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(block_id)

    def open_for_read(self, block: Block) -> Tuple[str, str, DataChecksum, int]:
        """Returns (data_path, meta_path, checksum, visible_length)."""
        with self._lock:
            rep = self._replicas.get(block.block_id)
        if rep is None:
            raise ReplicaNotFoundError(f"blk_{block.block_id} not on this node")
        if rep.gen_stamp < block.gen_stamp:
            raise ReplicaNotFoundError(
                f"blk_{block.block_id} replica genstamp {rep.gen_stamp} < "
                f"requested {block.gen_stamp}")
        data_path = self._path(rep.state, block.block_id)
        meta_path = data_path + ".meta"
        with open(meta_path, "rb") as f:
            f.seek(4 + 8)
            checksum = DataChecksum.from_header(
                f.read(DataChecksum.HEADER_LEN))
        return data_path, meta_path, checksum, rep.num_bytes

    def cache_block(self, block: Block) -> bool:
        """Pin a finalized replica's bytes in memory (ref: FsDatasetCache
        .cacheBlock). False when over the cache budget or not present."""
        with self._lock:
            if block.block_id in self._cached:
                return True
            used = sum(len(v) for v in self._cached.values())
        try:
            data_path, _, _, visible = self.open_for_read(block)
        except IOError:
            return False
        if used + visible > self.max_cache_bytes:
            return False
        with open(data_path, "rb") as f:
            data = f.read(visible)
        with self._lock:
            self._cached[block.block_id] = data
        return True

    def uncache_block(self, block_id: int) -> bool:
        with self._lock:
            return self._cached.pop(block_id, None) is not None

    def cached_ids(self) -> List[int]:
        with self._lock:
            return list(self._cached)

    def read_chunks(self, block: Block, offset: int, length: int,
                    opened=None):
        """Yield (chunk_aligned_offset, data, sums) runs for a byte range,
        chunk-aligned so the reader can CRC-verify; cached (memory-pinned)
        replicas serve data without touching the data file.
        Ref: BlockSender.java."""
        with self._lock:
            pinned = self._cached.get(block.block_id)
        if pinned is not None:
            yield from self._read_chunks_cached(block, offset, length,
                                                pinned)
            return
        data_path, meta_path, checksum, visible = \
            opened if opened is not None else self.open_for_read(block)
        bpc = checksum.bytes_per_chunk
        start = (offset // bpc) * bpc
        end = min(visible, offset + length)
        with open(data_path, "rb") as df, open(meta_path, "rb") as mf:
            # Sequential-read hint (ref: BlockSender's
            # manageOsCache POSIX_FADV_SEQUENTIAL): doubled readahead
            # for the scan, without polluting cache for other replicas.
            from hadoop_tpu import native
            native.fadvise(df.fileno(), start, max(0, end - start),
                           native.FADV_SEQUENTIAL)
            meta_header = 4 + 8 + DataChecksum.HEADER_LEN
            pos = start
            while pos < end:
                n = min(1024 * 1024, end - pos)
                # Round n up to chunk boundary (or EOF).
                n = min(((n + bpc - 1) // bpc) * bpc, visible - pos)
                df.seek(pos)
                data = df.read(n)
                first_chunk = pos // bpc
                n_chunks = (len(data) + bpc - 1) // bpc
                mf.seek(meta_header + 4 * first_chunk)
                sums = mf.read(4 * n_chunks)
                yield pos, data, sums
                pos += len(data)
                if len(data) < n:
                    break

    def _read_chunks_cached(self, block: Block, offset: int, length: int,
                            pinned: bytes):
        _, meta_path, checksum, visible = self.open_for_read(block)
        bpc = checksum.bytes_per_chunk
        start = (offset // bpc) * bpc
        end = min(visible, len(pinned), offset + length)
        meta_header = 4 + 8 + DataChecksum.HEADER_LEN
        with open(meta_path, "rb") as mf:
            pos = start
            while pos < end:
                n = min(1024 * 1024, end - pos)
                n = min(((n + bpc - 1) // bpc) * bpc, len(pinned) - pos)
                data = pinned[pos:pos + n]
                first_chunk = pos // bpc
                n_chunks = (len(data) + bpc - 1) // bpc
                mf.seek(meta_header + 4 * first_chunk)
                sums = mf.read(4 * n_chunks)
                yield pos, data, sums
                pos += len(data)
                if not data:
                    break

    # ------------------------------------------------------------ inventory

    def reconcile(self) -> Tuple[List[Block], List[Block]]:
        """DirectoryScanner diff of memory vs disk (ref: server/datanode/
        DirectoryScanner.java:64 reconcile): replicas whose data file
        vanished are dropped from memory (returned first — caller tells
        the NN so re-replication starts); orphaned finalized files with a
        valid meta are adopted (returned second — caller reports them
        received)."""
        with self._lock:
            snapshot = {bid: rep for bid, rep in self._replicas.items()
                        if bid not in self._open_writers}
        vanished: List[Block] = []
        for bid, rep in snapshot.items():
            if not os.path.exists(self._path(rep.state, bid)):
                vanished.append(rep.to_block())
                with self._lock:
                    if self._replicas.get(bid) is rep:
                        del self._replicas[bid]
        adopted: List[Block] = []
        fin_dir = os.path.join(self.dir, Replica.FINALIZED)
        for name in os.listdir(fin_dir):
            if not name.startswith("blk_") or name.endswith(".meta"):
                continue
            bid = int(name[4:])
            with self._lock:
                known = bid in self._replicas
            if known:
                continue
            data_path = os.path.join(fin_dir, name)
            gs = self._read_meta_genstamp(data_path + ".meta")
            if gs is None:
                continue  # torn orphan: no valid meta — leave for operator
            rep = Replica(bid, gs, os.path.getsize(data_path),
                          Replica.FINALIZED)
            with self._lock:
                self._replicas.setdefault(bid, rep)
            adopted.append(rep.to_block())
        return vanished, adopted

    def verify_replica(self, block: Block) -> None:
        """Full CRC sweep of one replica (VolumeScanner's unit of work).
        Raises ChecksumError on rot. Ref: VolumeScanner.java:55."""
        from hadoop_tpu.util.crc import DataChecksum
        _, _, checksum, visible = self.open_for_read(block)
        for pos, data, sums in self.read_chunks(block, 0, visible):
            checksum.verify(data, sums, base_pos=pos)

    def all_finalized(self) -> List[Block]:
        with self._lock:
            return [r.to_block() for r in self._replicas.values()
                    if r.state == Replica.FINALIZED]

    def stats(self) -> Dict[str, int]:
        used = 0
        with self._lock:
            n = len(self._replicas)
            for rep in self._replicas.values():
                used += rep.num_bytes
        if self.capacity_override:
            return {
                "capacity": self.capacity_override,
                "dfs_used": used,
                "remaining": max(0, self.capacity_override - used),
                "num_replicas": n,
            }
        st = os.statvfs(self.dir)
        return {
            "capacity": st.f_blocks * st.f_frsize,
            "dfs_used": used,
            "remaining": st.f_bavail * st.f_frsize,
            "num_replicas": n,
        }
