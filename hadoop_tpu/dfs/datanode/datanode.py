"""DataNode daemon: block store + streaming server + NN heartbeat actor.

Parity with the reference (ref: server/datanode/DataNode.java (3,788 LoC;
:1388 startDataNode, :2975 main), BPServiceActor.java:516 sendHeartBeat /
:643 offerService): registers with the NameNode, heartbeats on an interval
(NN commands ride the response), sends incremental "received/deleted" reports
promptly and full block reports periodically, and executes TRANSFER /
INVALIDATE / RECOVER commands.
"""

from __future__ import annotations

import logging
import os
import threading
import uuid as uuid_mod
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.datanode.blockstore import BlockStore
from hadoop_tpu.dfs.datanode.xceiver import DataXceiverServer, push_block
from hadoop_tpu.dfs.protocol.records import Block, DatanodeInfo, DnCommand
from hadoop_tpu.ipc import Client, get_proxy
from hadoop_tpu.service import AbstractService
from hadoop_tpu.util.misc import Daemon

log = logging.getLogger(__name__)


class DataNodeFaultInjector:
    """Overridable fault-injection points, compiled into the main code the
    way the reference does it (ref: server/datanode/DataNodeFaultInjector
    .java; call site DataXceiver.java:848). Tests install a subclass via
    ``DataNodeFaultInjector.set(instance)``."""

    _instance: "DataNodeFaultInjector" = None  # type: ignore[assignment]

    @classmethod
    def get(cls) -> "DataNodeFaultInjector":
        if cls._instance is None:
            cls._instance = DataNodeFaultInjector()
        return cls._instance

    @classmethod
    def set(cls, inst: Optional["DataNodeFaultInjector"]) -> None:
        cls._instance = inst

    # ---- hooks (no-ops by default) ----
    def before_write_block(self, block: Block) -> None: ...
    def before_packet_write(self, block: Block, pkt: dict) -> None: ...
    def before_read_block(self, block: Block, port: int = 0) -> None: ...
    def corrupt_read_packet(self, block, data, sums) -> Tuple[bytes, bytes]:
        return data, sums
    def before_heartbeat(self, dn: "DataNode") -> None: ...


class DataNode(AbstractService):
    """One actor loop per configured NameNode (ref: BPServiceActor — the
    DN heartbeats/reports to EVERY NN of the nameservice so standbys stay
    block-map-warm and promotion needs no report storm)."""

    def __init__(self, conf: Configuration, data_dir: Optional[str] = None,
                 nn_addr=None):
        super().__init__("DataNode")
        from hadoop_tpu.conf.keys import (
            DFS_DATANODE_DATA_DIR, DFS_DATANODE_DATA_DIR_DEFAULT,
            DFS_NAMENODE_RPC_ADDRESS, DFS_NAMENODE_RPC_ADDRESS_DEFAULT)
        # dfs.datanode.data.dir is a comma list (ref: FsVolumeList);
        # the first entry is the primary/metadata volume
        self.data_dir = data_dir or conf.get_list(
            DFS_DATANODE_DATA_DIR, [DFS_DATANODE_DATA_DIR_DEFAULT])[0]
        host = conf.get("dfs.datanode.hostname", "127.0.0.1")
        if nn_addr is None:
            from hadoop_tpu.util.misc import parse_addr_list
            self.nn_addrs = parse_addr_list(conf.get(
                DFS_NAMENODE_RPC_ADDRESS,
                DFS_NAMENODE_RPC_ADDRESS_DEFAULT))
        elif isinstance(nn_addr, tuple):
            self.nn_addrs = [nn_addr]
        else:
            self.nn_addrs = list(nn_addr)
        self.host = host
        self.uuid = self._load_or_create_uuid()
        self.store: Optional[BlockStore] = None
        self.xceiver: Optional[DataXceiverServer] = None
        self._client: Optional[Client] = None
        self._stop_event = threading.Event()
        self._actors: List["_BPServiceActor"] = []

    def _load_or_create_uuid(self) -> str:
        os.makedirs(self.data_dir, exist_ok=True)
        path = os.path.join(self.data_dir, "VERSION")
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    if line.startswith("datanodeUuid="):
                        return line.split("=", 1)[1].strip()
        u = str(uuid_mod.uuid4())
        with open(path, "w") as f:
            f.write(f"datanodeUuid={u}\n")
        return u

    @property
    def xfer_port(self) -> int:
        return self.xceiver.port

    def datanode_info(self) -> DatanodeInfo:
        stats = self.store.stats()
        http = getattr(self, "http", None)
        return DatanodeInfo(self.uuid, self.host, self.xceiver.port,
                            capacity=stats["capacity"],
                            dfs_used=stats["dfs_used"],
                            remaining=stats["remaining"],
                            storage_type=self.config.get(
                                "dfs.datanode.storage.type", "DISK"),
                            # admin-HTTP port rides registration (ref:
                            # DatanodeID.infoPort) so the NN's
                            # /ws/v1/datanodes roster can point the
                            # fleet doctor at this node's /ws/v1/peers
                            info_port=http.port if http else 0)

    # ------------------------------------------------------------- lifecycle

    def service_init(self, conf: Configuration) -> None:
        # Multi-volume node when dfs.datanode.data.dir lists several
        # directories (ref: a comma list backing FsVolumeList; the old
        # "data.dirs" spelling is a registered DeprecationDelta);
        # single-volume stays on the plain BlockStore.
        from hadoop_tpu.conf.keys import DFS_DATANODE_DATA_DIR
        dirs = conf.get_list(DFS_DATANODE_DATA_DIR)
        extra_dirs = dirs if len(dirs) > 1 else []
        n_vols = conf.get_int("dfs.datanode.volumes", 1)
        if not extra_dirs and n_vols > 1:
            extra_dirs = [os.path.join(self.data_dir, f"current{i}")
                          for i in range(n_vols)]
        cap = conf.get_size_bytes("dfs.datanode.capacity", 0)
        sync = conf.get_bool("dfs.datanode.synconclose", False)
        if len(extra_dirs) > 1:
            from hadoop_tpu.dfs.datanode.volumes import VolumeSet
            self.store = VolumeSet(
                [d.strip() for d in extra_dirs], capacity_override=cap,
                sync_on_close=sync,
                policy=conf.get("dfs.datanode.volume-choosing-policy",
                                "available-space"))
        else:
            self.store = BlockStore(
                extra_dirs[0].strip() if extra_dirs
                else os.path.join(self.data_dir, "current"),
                capacity_override=cap, sync_on_close=sync,
                drop_behind_writes=conf.get_bool(
                    "dfs.datanode.drop.cache.behind.writes", False))
        security_keys = None
        if conf.get_bool("dfs.encrypt.data.transfer", False):
            from hadoop_tpu.dfs.protocol.datatransfer import \
                DataEncryptionKeys
            security_keys = DataEncryptionKeys()
        self.xceiver = DataXceiverServer(
            self.store, self._on_block_received, bind_host=self.host,
            port=conf.get_int("dfs.datanode.port", 0),
            security_keys=security_keys,
            required_qop=conf.get("dfs.data.transfer.protection",
                                  "privacy"))
        # Block access tokens: verification-only manager, keys arrive
        # from the NN over DatanodeProtocol.get_block_keys (ref:
        # ExportedBlockKeys at registration + rotation refresh).
        self.block_tokens = None
        if conf.get_bool("dfs.block.access.token.enable", False):
            from hadoop_tpu.dfs.protocol.blocktoken import \
                BlockTokenSecretManager
            self.block_tokens = BlockTokenSecretManager.for_verification()
            self.xceiver.block_tokens = self.block_tokens
        # fd-passing short-circuit server (ref: dfs.domain.socket.path
        # with _PORT placeholder; DataXceiver.requestShortCircuitFds)
        self.domain_server = None
        self._domain_template = conf.get("dfs.domain.socket.path", "")
        self.heartbeat_interval = conf.get_time_seconds(
            "dfs.heartbeat.interval", 3.0)
        self.block_report_interval = conf.get_time_seconds(
            "dfs.blockreport.interval", 6 * 3600.0)
        # Background integrity scanners (0 = disabled).
        # Ref: dfs.datanode.scan.period.hours (VolumeScanner.java:55) and
        # dfs.datanode.directoryscan.interval (DirectoryScanner.java:64).
        self.volume_scan_interval = conf.get_time_seconds(
            "dfs.datanode.scan.period", 3 * 3600.0)
        self.dir_scan_interval = conf.get_time_seconds(
            "dfs.datanode.directoryscan.interval", 6 * 3600.0)
        # ref: dfs.datanode.max.locked.memory
        self.store.max_cache_bytes = conf.get_size_bytes(
            "dfs.datanode.max.locked.memory", 64 * 1024 * 1024)
        self._client = Client(conf)

    def service_start(self) -> None:
        self.xceiver.start()
        if self._domain_template:
            from hadoop_tpu.dfs.datanode.domainsocket import (
                DomainPeerServer, socket_path_for)
            checker = None
            if self.block_tokens is not None:
                from hadoop_tpu.dfs.protocol import blocktoken as bt

                def checker(req, block):
                    self.block_tokens.check_access(
                        req.get("tok"), block.block_id, bt.MODE_READ)
            self.domain_server = DomainPeerServer(
                socket_path_for(self._domain_template, self.xceiver.port),
                self.store.open_for_read, token_checker=checker)
            self.domain_server.start()
            self.xceiver.domain_socket_path = self.domain_server.path
        self.http = None
        if self.config.get_bool("dfs.datanode.http.enabled", True):
            from hadoop_tpu.http import HttpServer
            self.http = HttpServer(
                self.config,
                bind=("127.0.0.1",
                      self.config.get_int("dfs.datanode.http-port", 0)),
                daemon_name=f"datanode-{self.uuid[:8]}")
            self.http.add_handler(
                "/blockstats", lambda q, b: (200, self.store.stats()))
            self.http.add_handler(
                "/diskbalancer", self._diskbalancer_endpoint)
            # rolling per-peer pipeline latencies + own service times —
            # what the fleet doctor's slow-node detection scrapes
            self.http.add_handler(
                "/ws/v1/peers",
                lambda q, b: (200,
                              self.xceiver.peer_tracker.to_report(
                                  self.uuid)))
            self.http.start()
        for addr in self.nn_addrs:
            actor = _BPServiceActor(self, addr)
            self._actors.append(actor)
            actor.start()
        if self.volume_scan_interval > 0:
            Daemon(self._volume_scan_loop,
                   f"volume-scanner-{self.uuid[:8]}").start()
        if self.dir_scan_interval > 0:
            Daemon(self._dir_scan_loop,
                   f"directory-scanner-{self.uuid[:8]}").start()
        log.info("DataNode %s up (xfer port %d, NNs %s)", self.uuid[:8],
                 self.xceiver.port, self.nn_addrs)

    def service_stop(self) -> None:
        self._stop_event.set()
        if getattr(self, "http", None) is not None:
            self.http.stop()
        if getattr(self, "domain_server", None) is not None:
            self.domain_server.stop()
        if self.xceiver:
            self.xceiver.stop()
        if self._client:
            self._client.stop()

    # ---------------------------------------------------------- NN reporting

    @property
    def nn_addr(self):
        """First NN address (compat for single-NN callers/tests)."""
        return self.nn_addrs[0]

    @nn_addr.setter
    def nn_addr(self, addr) -> None:
        self.nn_addrs[0] = addr
        if self._actors:
            self._actors[0].nn_addr = addr

    def _on_block_received(self, block: Block) -> None:
        for actor in self._actors:
            actor.note_received(block)

    def _on_block_deleted(self, block: Block) -> None:
        for actor in self._actors:
            actor.note_deleted(block)

    def _diskbalancer_endpoint(self, query, body):
        """report/plan/execute over the admin HTTP surface (the reference
        drives these over ClientDatanodeProtocol:
        submitDiskBalancerPlan/queryDiskBalancerPlan)."""
        from hadoop_tpu.dfs.datanode.volumes import DiskBalancer, VolumeSet
        action = query.get("action", "report")
        if not isinstance(self.store, VolumeSet):
            return 400, {"error": "not a multi-volume datanode"}
        db = DiskBalancer(self.store)
        if action == "report":
            return 200, db.report()
        threshold = float(query.get("threshold", 0.10))
        plan = db.plan(threshold)
        if action == "plan":
            return 200, {"moves": plan}
        if action == "execute":
            return 200, db.execute(plan)
        return 400, {"error": f"unknown action {action!r}"}

    # -------------------------------------------------------------- scanners

    def _report_cached(self) -> None:
        ids = self.store.cached_ids()
        for actor in self._actors:
            try:
                if actor._proxy is not None:
                    actor._proxy.report_cached(self.uuid, ids)
            except Exception as e:  # noqa: BLE001
                log.debug("cache report to %s failed: %s",
                          actor.nn_addr, e)

    def _report_bad_block(self, block: Block) -> None:
        """Self-detected rot → every NN (ref: the VolumeScanner's
        reportBadBlocks path through the BPOS)."""
        for actor in self._actors:
            try:
                if actor._proxy is not None:
                    actor._proxy.report_bad_blocks(
                        [block.to_wire()], [self.uuid])
            except Exception as e:  # noqa: BLE001 — next heartbeat retries
                log.warning("bad-block report to %s failed: %s",
                            actor.nn_addr, e)

    def _volume_scan_loop(self) -> None:
        """Slow CRC sweep: one full pass over finalized replicas per
        period, spread evenly. Ref: VolumeScanner.java:55 (its
        bytes-per-second throttle becomes an even per-period spread)."""
        from hadoop_tpu.util.crc import ChecksumError
        while not self._stop_event.is_set():
            blocks = self.store.all_finalized()
            pause = self.volume_scan_interval / max(len(blocks), 1)
            for block in blocks:
                if self._stop_event.wait(min(pause,
                                             self.volume_scan_interval)):
                    return
                try:
                    self.store.verify_replica(block)
                except ChecksumError as e:
                    log.warning("Volume scanner found rot in %s: %s",
                                block, e)
                    self._report_bad_block(block)
                except IOError:
                    pass  # replica finalized/invalidated mid-scan
            if not blocks and self._stop_event.wait(
                    self.volume_scan_interval):
                return

    def _dir_scan_loop(self) -> None:
        """Memory↔disk reconciliation. Ref: DirectoryScanner.java:64."""
        while not self._stop_event.wait(self.dir_scan_interval):
            try:
                vanished, adopted = self.store.reconcile()
            except OSError as e:
                log.warning("directory scan failed: %s", e)
                continue
            for block in vanished:
                log.warning("Directory scanner: replica %s vanished from "
                            "disk", block)
                # report it DELETED (it is): the NN drops this location
                # and re-replicates from the healthy copies — a bad-block
                # report would dead-end in invalidating a missing file
                self._on_block_deleted(block)
            for block in adopted:
                log.info("Directory scanner: adopted on-disk replica %s",
                         block)
                self._on_block_received(block)

    # -------------------------------------------------------------- commands

    def _execute(self, cmd: DnCommand) -> bool:
        """Returns False to force re-registration."""
        if cmd.action == DnCommand.REREGISTER:
            return False
        if cmd.action == DnCommand.INVALIDATE:
            for b in cmd.blocks:
                if self.store.invalidate(b):
                    self._on_block_deleted(b)
        elif cmd.action == DnCommand.TRANSFER:
            for block, targets in zip(cmd.blocks, cmd.targets):
                Daemon(self._transfer, "dn-transfer",
                       args=(block, targets)).start()
        elif cmd.action == DnCommand.EC_RECONSTRUCT:
            Daemon(self._ec_reconstruct, "dn-ec-worker",
                   args=(cmd.extra,)).start()
        elif cmd.action == DnCommand.CACHE:
            # pin replicas in memory + report the new cached set (ref:
            # FsDatasetCache.cacheBlock + DatanodeProtocol.cacheReport)
            for b in cmd.blocks:
                if not self.store.cache_block(b):
                    log.info("could not cache %s (budget/missing)", b)
            self._report_cached()
        elif cmd.action == DnCommand.UNCACHE:
            for b in cmd.blocks:
                self.store.uncache_block(b.block_id)
            self._report_cached()
        elif cmd.action == DnCommand.RECOVER:
            # Block recovery: bump the stamp and promote the rbw replica to
            # finalized at its current length, then report it.
            # Ref: DataNode.recoverBlocks / BlockRecoveryWorker.
            for block, new_gs in zip(cmd.blocks, cmd.new_gen_stamps):
                try:
                    self.store.update_gen_stamp(block.block_id, new_gs)
                    rep = self.store.finalize_existing(block.block_id)
                    if rep is not None:
                        self._on_block_received(rep.to_block())
                except IOError as e:
                    log.warning("recover of %s failed: %s", block, e)
        return True

    def _ec_reconstruct(self, payload: Dict) -> None:
        """Ref: ErasureCodingWorker.processErasureCodingTasks."""
        from hadoop_tpu.dfs.datanode import ec_worker
        rebuilt = ec_worker.reconstruct(
            self.store, payload, security=self.xceiver._dial_security(),
            block_tokens=self.block_tokens)
        if rebuilt is not None:
            self._on_block_received(rebuilt)

    def _transfer(self, block: Block, targets) -> None:
        try:
            rep = self.store.get_replica(block.block_id)
            if rep is None:
                log.warning("asked to transfer %s but replica not found", block)
                return
            push_block(self.store, rep.to_block(), targets,
                       block_tokens=self.block_tokens,
                       security=self.xceiver._dial_security())
            log.info("Transferred %s to %s", block, targets)
        except Exception as e:  # noqa: BLE001
            log.warning("transfer of %s failed: %s", block, e)


class _BPServiceActor:
    """One DN→NN reporting loop. Ref: server/datanode/BPServiceActor.java
    (:516 sendHeartBeat, :643 offerService)."""

    def __init__(self, dn: DataNode, nn_addr: Tuple[str, int]):
        self.dn = dn
        self.nn_addr = nn_addr
        self._lock = threading.Lock()
        self._received: List[Block] = []
        self._deleted: List[Block] = []
        self._proxy = None
        # Immediate-IBR wake (ref: BPServiceActor.sendImmediateIBR /
        # triggerBlockReportForTests): a finalized replica must reach
        # the NN NOW, not on the next heartbeat tick — the client's
        # completeFile() polls with backoff, so a heartbeat-cadence IBR
        # turns every small-file close into a ~0.75 s stall.
        self._wake = threading.Event()

    def start(self) -> None:
        Daemon(self._offer_service,
               f"bp-actor-{self.dn.uuid[:8]}-{self.nn_addr[1]}").start()

    def note_received(self, block: Block) -> None:
        with self._lock:
            self._received.append(block)
        self._wake.set()

    def note_deleted(self, block: Block) -> None:
        with self._lock:
            self._deleted.append(block)
        self._wake.set()

    def _offer_service(self) -> None:
        """Main actor loop. Ref: BPServiceActor.offerService:643."""
        dn = self.dn
        registered = False
        last_full_report = 0.0
        import time as _time
        self._proxy = get_proxy("DatanodeProtocol", self.nn_addr,
                                client=dn._client)
        # PROVIDED storage: the xceiver resolves block aliases through
        # this NN (any actor's proxy works; last writer wins).
        dn.xceiver.alias_resolver = \
            lambda bid: self._proxy.get_block_alias(bid)
        while not dn._stop_event.is_set():
            try:
                if not registered:
                    self._proxy.register_datanode(
                        dn.datanode_info().to_wire())
                    if dn.xceiver.security_keys is not None:
                        dn.xceiver.security_keys.update(
                            self._proxy.get_data_encryption_keys())
                    if dn.block_tokens is not None:
                        dn.block_tokens.import_keys(
                            self._proxy.get_block_keys())
                    registered = True
                    self._send_full_report()
                    last_full_report = _time.monotonic()
                self._flush_incremental_reports()
                DataNodeFaultInjector.get().before_heartbeat(dn)
                stats = dn.store.stats()
                cmds = self._proxy.send_heartbeat(
                    dn.uuid, stats["capacity"], stats["dfs_used"],
                    stats["remaining"], dn.xceiver.active_xceivers)
                for c in cmds:
                    registered &= dn._execute(DnCommand.from_wire(c))
                if _time.monotonic() - last_full_report > \
                        dn.block_report_interval:
                    self._send_full_report()
                    if dn.xceiver.security_keys is not None:
                        # Piggyback key refresh: the report interval (6h)
                        # is inside the key-rotation window (10h TTL,
                        # rotated at 80%), so a DN never serves with only
                        # expired keys.
                        dn.xceiver.security_keys.update(
                            self._proxy.get_data_encryption_keys())
                    if dn.block_tokens is not None:
                        dn.block_tokens.import_keys(
                            self._proxy.get_block_keys())
                    last_full_report = _time.monotonic()
            except Exception as e:  # noqa: BLE001 — survive NN bounces
                log.debug("heartbeat round to %s failed (%s); will retry",
                          self.nn_addr, e)
                registered = False
                # NN may have restarted on a new address (minicluster) —
                # rebuild the proxy from the current nn_addr.
                self._proxy = get_proxy("DatanodeProtocol", self.nn_addr,
                                        client=dn._client)
            # Sleep until the next heartbeat, but wake early to flush
            # incremental reports the moment a block lands/deletes.
            deadline = _time.monotonic() + dn.heartbeat_interval
            while not dn._stop_event.is_set():
                rem = deadline - _time.monotonic()
                if rem <= 0:
                    break
                if not self._wake.wait(timeout=min(rem, 0.25)):
                    continue
                self._wake.clear()
                try:
                    self._flush_incremental_reports()
                except Exception:  # noqa: BLE001 — NN bounce
                    registered = False
                    break  # next outer iteration rebuilds + re-registers

    def _send_full_report(self) -> None:
        blocks = [b.to_wire() for b in self.dn.store.all_finalized()]
        self._proxy.block_report(self.dn.uuid, blocks)

    def _flush_incremental_reports(self) -> None:
        with self._lock:
            received, self._received = self._received, []
            deleted, self._deleted = self._deleted, []
        if received or deleted:
            try:
                self._proxy.block_received_and_deleted(
                    self.dn.uuid, [b.to_wire() for b in received],
                    [b.to_wire() for b in deleted])
            except Exception:
                # NN unreachable/bouncing: put the reports BACK — a
                # dropped IBR means the NN never learns the replica
                # exists until the next full report (hours).
                with self._lock:
                    self._received[:0] = received
                    self._deleted[:0] = deleted
                # No _wake.set() here: the next heartbeat-cadence flush
                # retries; waking now would busy-spin against a dead NN.
                raise
