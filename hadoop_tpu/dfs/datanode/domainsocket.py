"""AF_UNIX fd-passing server: short-circuit grants without path handoff.

Parity with the reference's domain-socket transport (ref:
hadoop-common/src/main/native/src/org/apache/hadoop/net/unix/
DomainSocket.c; server side DataXceiver.requestShortCircuitFds;
configured by ``dfs.domain.socket.path`` with a ``_PORT`` placeholder):
a same-host client connects to the DN's Unix socket, presents the
block (+ its access token when ``dfs.block.access.token.enable`` is
on), and receives the replica's OPEN file descriptors via
``SCM_RIGHTS`` — the DN never reveals filesystem paths, so possession
of a grant is bounded by the token check, not by directory
permissions. Python's ``socket.send_fds``/``recv_fds`` replace the
reference's JNI layer.

Revocation model: an fd snapshot of a FINALIZED replica stays
byte-correct even if the balancer later moves or deletes the file
(POSIX keeps unlinked data readable through open fds), and append/
recovery bumps the genstamp, which changes the client's cache key —
so no shared-memory slot-revocation plane (ShortCircuitShm.java) is
needed for correctness; the reference adds it to reclaim space
eagerly, which this design trades for simplicity.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
from typing import Callable, Optional

from hadoop_tpu.dfs.protocol import datatransfer as dt
from hadoop_tpu.dfs.protocol.records import Block
from hadoop_tpu.io import pack, unpack
from hadoop_tpu.security.ugi import AccessControlError
from hadoop_tpu.util.misc import Daemon

log = logging.getLogger(__name__)

MAX_REQ = 1 << 20


class DomainPeerServer:
    """Per-DN Unix-socket listener serving REQUEST_FDS.

    ``token_checker(req, block)`` raises AccessControlError to refuse
    (None = tokens disabled). ``open_for_read`` is the blockstore's
    resolver returning (data_path, meta_path, checksum, visible).
    """

    def __init__(self, path: str, open_for_read: Callable,
                 token_checker: Optional[Callable] = None):
        self.path = path
        self.open_for_read = open_for_read
        self.token_checker = token_checker
        self._lsock: Optional[socket.socket] = None
        self._running = False
        self.grants = 0

    def start(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._lsock.bind(self.path)
        # rw for owner only: the socket itself is the first gate
        os.chmod(self.path, 0o600)
        self._lsock.listen(64)
        self._running = True
        Daemon(self._accept_loop,
               f"domain-peer-{os.path.basename(self.path)}").start()

    def stop(self) -> None:
        self._running = False
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, _ = self._lsock.accept()
            except OSError:
                return
            Daemon(self._serve, "domain-peer-conn", args=(sock,)).start()

    def _serve(self, sock: socket.socket) -> None:
        try:
            with sock:
                sock.settimeout(10.0)
                try:
                    from hadoop_tpu.io.wire import read_frame
                    req = unpack(read_frame(sock, MAX_REQ))
                except (OSError, EOFError, ValueError):
                    return
                if not isinstance(req, dict) or "b" not in req:
                    self._reply(sock, {"ok": False, "em": "bad request"})
                    return
                block = Block.from_wire(req["b"])
                if self.token_checker is not None:
                    try:
                        self.token_checker(req, block)
                    except AccessControlError as e:
                        self._reply(sock, {"ok": False, "em": str(e),
                                           "denied": True})
                        return
                try:
                    data_path, meta_path, checksum, visible = \
                        self.open_for_read(block)
                except IOError as e:
                    self._reply(sock, {"ok": False, "em": str(e)})
                    return
                data_fd = meta_fd = -1
                try:
                    data_fd = os.open(data_path, os.O_RDONLY)
                    meta_fd = os.open(meta_path, os.O_RDONLY)
                    frame = pack({"ok": True,
                                  "bpc": checksum.bytes_per_chunk,
                                  "visible": visible})
                    socket.send_fds(
                        sock, [struct.pack(">I", len(frame)) + frame],
                        [data_fd, meta_fd])
                    self.grants += 1
                except OSError as e:
                    log.debug("fd grant for %s failed: %s", block, e)
                finally:
                    # the kernel dup'ed them into the message; close ours
                    for fd in (data_fd, meta_fd):
                        if fd >= 0:
                            try:
                                os.close(fd)
                            except OSError:
                                pass
        except Exception:  # noqa: BLE001 — one bad peer must not kill the loop
            log.debug("domain peer connection error", exc_info=True)

    @staticmethod
    def _reply(sock: socket.socket, msg: dict) -> None:
        from hadoop_tpu.io.wire import write_frame
        try:
            write_frame(sock, pack(msg))
        except OSError:
            pass


def socket_path_for(template: str, xfer_port: int) -> str:
    """Expand the ``_PORT`` placeholder (ref: DomainSocket.getEffectivePath
    applied to dfs.domain.socket.path)."""
    return template.replace("_PORT", str(xfer_port))
