"""Erasure-coding reconstruction worker for the DataNode.

Parity with the reference's DN-side EC machinery (ref:
server/datanode/erasurecode/ErasureCodingWorker.java:47,
StripedBlockReconstructor.java:34, StripedReader/StripedWriter): given an
EC_RECONSTRUCT command, read the stripe cells of k surviving units from
peer DataNodes, decode the missing unit with the policy's raw coder, and
store it as a local finalized replica (reported back to the NameNode via
the normal incremental block report).

Reconstruction proceeds stripe-run by stripe-run (``SPAN_CELLS`` cells
per source read) so memory stays bounded regardless of block size.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.dfs.protocol import datatransfer as dt
from hadoop_tpu.dfs.protocol.records import Block, DatanodeInfo
from hadoop_tpu.io import erasurecode as ec
from hadoop_tpu.util.crc import DataChecksum

log = logging.getLogger(__name__)

SPAN_CELLS = 64  # cells fetched per source round (64 × 64 KB = 4 MB)


def fetch_range(addr: Tuple[str, int], block: Block, offset: int,
                length: int, security=None, block_tokens=None) -> bytes:
    """Read [offset, offset+length) of a remote replica (OP_READ_BLOCK).
    A reconstructing DN mints its own READ token from the shared keys
    (ref: the reconstruction worker's datanode-issued tokens)."""
    token = None
    if block_tokens is not None:
        from hadoop_tpu.dfs.protocol import blocktoken as bt
        token = block_tokens.generate_token("datanode", block.block_id,
                                            (bt.MODE_READ,))
    return dt.read_block_range(addr, block.to_wire(), offset, length,
                               security=security, token=token)


def reconstruct(store, payload: Dict, security=None,
                block_tokens=None) -> Optional[Block]:
    """Execute one EC_RECONSTRUCT command; returns the rebuilt unit block
    (for the incremental report) or None on failure."""
    group = Block.from_wire(payload["group"])
    policy = ec.get_policy(payload["policy"])
    missing_idx: int = payload["idx"]
    sources: List[Tuple[DatanodeInfo, int]] = [
        (DatanodeInfo.from_wire(w), idx) for w, idx in payload["sources"]]

    k, cell = policy.k, policy.cell_size
    target_len = ec.unit_length(group.num_bytes, policy, missing_idx)
    unit = Block(group.block_id + missing_idx, group.gen_stamp, target_len)

    # Pick k sources, preferring data units (cheaper decode is not a thing
    # for RS, but data-unit lengths define the stripe widths).
    sources = sorted(sources, key=lambda s: s[1])[:policy.num_units]
    by_idx = {idx: info for info, idx in sources}

    checksum = DataChecksum(dt.CHUNK_SIZE)
    open_rep = store.create_rbw(unit, checksum)
    try:
        built = 0
        stripe = 0
        while built < target_len:
            # One span: SPAN_CELLS stripes' worth of cells per source.
            span_shards: List[Optional[bytes]] = [None] * policy.num_units
            got = 0
            span_stripes = SPAN_CELLS
            for idx in range(policy.num_units):
                if got >= k:
                    break
                if idx == missing_idx or idx not in by_idx:
                    continue
                src_len = ec.unit_length(group.num_bytes, policy, idx)
                off = stripe * cell
                want = min(span_stripes * cell, max(0, src_len - off))
                blk = Block(group.block_id + idx, group.gen_stamp, src_len)
                try:
                    raw = fetch_range(by_idx[idx].xfer_addr(), blk, off,
                                      want, security=security,
                                      block_tokens=block_tokens)
                except (OSError, EOFError, IOError) as e:
                    log.warning("EC source unit %d unreadable: %s", idx, e)
                    continue
                span_shards[idx] = raw
                got += 1
            if got < k:
                raise IOError(f"only {got} of {k} EC sources readable")
            # Decode stripe by stripe within the span.
            for s in range(span_stripes):
                if built >= target_len:
                    break
                widths = [
                    max(0, min(group.num_bytes
                               - ((stripe + s) * k + i) * cell, cell))
                    for i in range(k)]
                width = max(widths)
                if width == 0:
                    break
                shards: List[Optional[bytes]] = [None] * policy.num_units
                for idx, span in enumerate(span_shards):
                    if span is None:
                        continue
                    frag = span[s * cell:s * cell + width]
                    if len(frag) < width:
                        frag = frag + b"\0" * (width - len(frag))
                    shards[idx] = frag
                full = policy.new_coder().decode(shards)
                want_w = widths[missing_idx] if missing_idx < k else width
                piece = full[missing_idx][:want_w]
                piece = piece[:target_len - built]
                if piece:
                    open_rep.write_packet(piece,
                                          checksum.checksums_for(piece))
                    built += len(piece)
            stripe += span_stripes
        rep = store.finalize(open_rep)
        log.info("Reconstructed EC unit %s (%d bytes)", unit, built)
        return rep.to_block()
    except Exception as e:  # noqa: BLE001 — report and let NN reschedule
        log.warning("EC reconstruction of %s failed: %s", unit, e)
        try:
            open_rep.abort()
        except (OSError, IOError) as e2:
            log.debug("EC replica abort failed: %s", e2)
        return None
