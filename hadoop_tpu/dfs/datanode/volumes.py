"""Multi-volume DataNode dataset + the intra-node DiskBalancer.

``VolumeSet`` presents the single-``BlockStore`` API over N data
directories, one ``BlockStore`` per volume (ref: fsdataset/impl/
FsVolumeList.java — volumes each own their replica map; the dataset
routes by block). New replicas pick a volume by available space (ref:
AvailableSpaceVolumeChoosingPolicy.java; ``policy="round-robin"`` for
RoundRobinVolumeChoosingPolicy.java).

``DiskBalancer`` rebalances replicas *between volumes of one node*
(ref: hadoop-hdfs server/diskbalancer/ — DiskBalancerCluster computes
volume-density deltas, planner emits MoveStep's, DiskBalancerMover
copies block files volume→volume). The reference drives it over
ClientDatanodeProtocol (submitDiskBalancerPlan); here the DataNode
exposes report/plan/execute over its admin HTTP endpoint and in-process.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.dfs.datanode.blockstore import (BlockStore, Replica,
                                                ReplicaNotFoundError)

log = logging.getLogger(__name__)


class VolumeSet:
    """N BlockStores behind the BlockStore API, routed by replica."""

    def __init__(self, directories: List[str], chunk_size: int = 512,
                 capacity_override: int = 0, sync_on_close: bool = False,
                 policy: str = "available-space"):
        if not directories:
            raise ValueError("VolumeSet needs at least one directory")
        per_vol_cap = capacity_override // len(directories) \
            if capacity_override else 0
        self.volumes = [BlockStore(d, chunk_size=chunk_size,
                                   capacity_override=per_vol_cap,
                                   sync_on_close=sync_on_close)
                        for d in directories]
        self.policy = policy
        self._rr = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------- routing

    @property
    def dir(self) -> str:  # compat with single-volume callers
        return self.volumes[0].dir

    def _vol_of(self, block_id: int) -> Optional[BlockStore]:
        for v in self.volumes:
            if v.get_replica(block_id) is not None:
                return v
        return None

    def _vol_or_raise(self, block_id: int) -> BlockStore:
        v = self._vol_of(block_id)
        if v is None:
            raise ReplicaNotFoundError(
                f"blk_{block_id} not on this node")
        return v

    def _choose(self) -> BlockStore:
        if self.policy == "round-robin":
            with self._lock:
                v = self.volumes[self._rr % len(self.volumes)]
                self._rr += 1
                return v
        return max(self.volumes, key=lambda v: v.stats()["remaining"])

    # ------------------------------------------------- delegated write path

    def create_rbw(self, block, checksum):
        # Pipeline recovery must land on the volume that already holds
        # the rbw replica (the writer rebind logic lives in that store).
        v = self._vol_of(block.block_id) or self._choose()
        return v.create_rbw(block, checksum)

    def finalize(self, open_rep) -> Replica:
        return open_rep.store.finalize(open_rep)

    def invalidate(self, block) -> bool:
        v = self._vol_of(block.block_id)
        return v.invalidate(block) if v is not None else False

    def finalize_existing(self, block_id: int) -> Optional[Replica]:
        return self._vol_or_raise(block_id).finalize_existing(block_id)

    def update_gen_stamp(self, block_id: int, new_gs: int) -> None:
        self._vol_or_raise(block_id).update_gen_stamp(block_id, new_gs)

    # -------------------------------------------------- delegated read path

    def get_replica(self, block_id: int) -> Optional[Replica]:
        v = self._vol_of(block_id)
        return v.get_replica(block_id) if v is not None else None

    def open_for_read(self, block):
        return self._vol_or_raise(block.block_id).open_for_read(block)

    def read_chunks(self, block, offset: int, length: int, opened=None):
        # ``opened`` is the xceiver's eager open_for_read probe result —
        # must be accepted (and forwarded) or every read on a
        # multi-volume DN dies with TypeError before the setup reply
        return self._vol_or_raise(block.block_id).read_chunks(
            block, offset, length, opened=opened)

    def verify_replica(self, block) -> None:
        self._vol_or_raise(block.block_id).verify_replica(block)

    def cache_block(self, block) -> bool:
        v = self._vol_of(block.block_id)
        return v.cache_block(block) if v is not None else False

    def uncache_block(self, block_id: int) -> bool:
        return any(v.uncache_block(block_id) for v in self.volumes)

    def cached_ids(self) -> List[int]:
        return [b for v in self.volumes for b in v.cached_ids()]

    def _path(self, state: str, block_id: int) -> str:
        v = self._vol_of(block_id)
        return (v or self.volumes[0])._path(state, block_id)

    # ----------------------------------------------------------- inventory

    def reconcile(self):
        vanished: List = []
        adopted: List = []
        for v in self.volumes:
            gone, found = v.reconcile()
            vanished.extend(gone)
            adopted.extend(found)
        return vanished, adopted

    def all_finalized(self):
        return [b for v in self.volumes for b in v.all_finalized()]

    def stats(self) -> Dict[str, int]:
        agg = {"capacity": 0, "dfs_used": 0, "remaining": 0,
               "num_replicas": 0}
        for v in self.volumes:
            s = v.stats()
            for k in agg:
                agg[k] += s.get(k, 0)
        return agg

    @property
    def max_cache_bytes(self) -> int:
        return sum(v.max_cache_bytes for v in self.volumes)

    @max_cache_bytes.setter
    def max_cache_bytes(self, total: int) -> None:
        per = total // len(self.volumes)
        for v in self.volumes:
            v.max_cache_bytes = per

    def volume_stats(self) -> List[Dict[str, int]]:
        out = []
        for v in self.volumes:
            s = v.stats()
            s["dir"] = v.dir
            out.append(s)
        return out

    # ------------------------------------------------- volume→volume moves

    def move_replica(self, block_id: int, dst_index: int) -> bool:
        """Copy one finalized replica onto ``volumes[dst_index]`` and
        retire the source copy (the DiskBalancerMover unit of work)."""
        dst = self.volumes[dst_index]
        src = self._vol_of(block_id)
        if src is None or src is dst:
            return False
        rep = src.get_replica(block_id)
        if rep is None or rep.state != Replica.FINALIZED:
            return False
        sdata = src._path(Replica.FINALIZED, block_id)
        ddata = dst._path(Replica.FINALIZED, block_id)
        tmp = ddata + ".dbtmp"
        try:
            shutil.copyfile(sdata, tmp)
            shutil.copyfile(sdata + ".meta", tmp + ".meta")
            # Commit meta first, data last: if the second replace fails,
            # the dst holds at worst an orphan .meta (ignored by the
            # directory scanner), never a finalized data file without a
            # .meta that a later reconcile could adopt as corrupt.
            os.replace(tmp + ".meta", ddata + ".meta")
            os.replace(tmp, ddata)
        except OSError as e:
            log.warning("disk-balancer move of blk_%d failed: %s",
                        block_id, e)
            for p in (tmp, tmp + ".meta", ddata + ".meta"):
                try:
                    if os.path.exists(p):
                        os.remove(p)
                except OSError:
                    pass
            return False
        with dst._lock:
            dst._replicas[block_id] = Replica(
                block_id, rep.gen_stamp, rep.num_bytes, Replica.FINALIZED)
        src.invalidate(rep.to_block())
        return True


class DiskBalancer:
    """Plan/execute volume rebalancing for one DataNode.

    Ref: server/diskbalancer/planner/GreedyPlanner.java — move bytes
    from volumes above the node's mean utilization to volumes below it
    until every volume is within ``threshold`` of the mean.
    """

    def __init__(self, store: VolumeSet):
        if not isinstance(store, VolumeSet):
            raise ValueError("disk balancer requires a multi-volume node")
        self.store = store

    def report(self) -> Dict:
        vols = self.store.volume_stats()
        node = self.store.stats()
        node_util = node["dfs_used"] / max(1, node["capacity"])
        for s in vols:
            s["utilization"] = round(
                s["dfs_used"] / max(1, s["capacity"]), 4)
            s["density"] = round(s["utilization"] - node_util, 4)
        return {"node_utilization": round(node_util, 4), "volumes": vols}

    def plan(self, threshold: float = 0.10) -> List[Dict]:
        """[{block_id, src, dst, bytes}] bringing volumes within
        threshold of the mean."""
        rep = self.report()
        vols = rep["volumes"]
        moves: List[Dict] = []
        # Work on mutable copies of used-bytes.
        used = [s["dfs_used"] for s in vols]
        cap = [max(1, s["capacity"]) for s in vols]
        mean = sum(used) / max(1, sum(cap))

        def density(i):
            return used[i] / cap[i] - mean

        for si, sv in enumerate(self.store.volumes):
            blocks = sorted(sv.all_finalized(), key=lambda b: -b.num_bytes)
            for b in blocks:
                if density(si) <= threshold:
                    break
                di = min(range(len(used)), key=density)
                if di == si or density(di) >= -1e-9:
                    break
                moves.append({"block_id": b.block_id, "src": si, "dst": di,
                              "bytes": b.num_bytes})
                used[si] -= b.num_bytes
                used[di] += b.num_bytes
        return moves

    def execute(self, moves: List[Dict]) -> Dict[str, int]:
        done = failed = 0
        for m in moves:
            if self.store.move_replica(m["block_id"], m["dst"]):
                done += 1
            else:
                failed += 1
        return {"moved": done, "failed": failed}
