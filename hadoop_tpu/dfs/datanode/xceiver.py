"""Streaming data server: thread-per-transfer, pipelined writes, chunked reads.

Parity with the reference's xceiver layer (ref:
server/datanode/DataXceiverServer.java:48/:222 run, DataXceiver.java:667
writeBlock (mirror connect at :831), BlockReceiver.java:953 receiveBlock +
PacketResponder (:975), BlockSender.java):

WRITE_BLOCK: accept op → connect downstream mirror (remaining targets) →
ack the setup upstream → receive packets: CRC-verify, write, forward; a
responder thread relays downstream acks upstream with this node's status
prepended. The terminal node acks directly. Last packet (empty, last=True)
finalizes the replica and queues an incremental block report.

READ_BLOCK: stream chunk-aligned packets with their stored checksums (client
verifies; a checksum error at the client marks the replica corrupt at the NN).
"""

from __future__ import annotations

import contextlib
import logging
import socket
import threading
import time
from typing import Callable, List, Optional

from hadoop_tpu.dfs.protocol import datatransfer as dt
from hadoop_tpu.dfs.protocol.records import Block, DatanodeInfo
from hadoop_tpu.dfs.datanode.blockstore import BlockStore
from hadoop_tpu.metrics import metrics_system
from hadoop_tpu.tracing.tracer import (SpanContext, current_span,
                                       global_tracer)
from hadoop_tpu.util.crc import ChecksumError, DataChecksum
from hadoop_tpu.util.misc import Daemon

log = logging.getLogger(__name__)


class DataXceiverServer:
    def __init__(self, store: BlockStore,
                 on_block_received: Callable[[Block], None],
                 bind_host: str = "127.0.0.1", port: int = 0,
                 fault_injector=None, security_keys=None,
                 required_qop: str = "privacy"):
        self.store = store
        self.on_block_received = on_block_received
        # DataEncryptionKeys when dfs.encrypt.data.transfer is on: every
        # accepted socket must SASL-handshake (ref:
        # SaslDataTransferServer.java), and mirror/push dials handshake
        # with the newest key.
        self.security_keys = security_keys
        self.required_qop = required_qop
        # PROVIDED storage: block id → external alias resolver (wired by
        # the DataNode once it has an NN proxy; ref: ProvidedVolumeImpl
        # reading through the alias map). Cache hits avoid per-read RPCs.
        self.alias_resolver = None
        self.domain_socket_path = None     # set by the owning DataNode
        self.block_tokens = None           # set by the owning DataNode
        self._alias_cache: dict = {}       # block id → (alias, expiry)
        self.ALIAS_CACHE_TTL = 60.0
        self.ALIAS_CACHE_MAX = 4096
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((bind_host, port))
        self._lsock.listen(128)
        # Closing a listening socket does NOT wake a thread blocked in
        # accept(2) on Linux; a periodic timeout lets the accept loop see
        # _running flip and exit instead of leaking (accepted sockets are
        # unaffected — they come back in blocking mode).
        self._lsock.settimeout(0.5)
        self.port = self._lsock.getsockname()[1]
        self._running = False
        self.active_xceivers = 0
        # Explicit injector wins; otherwise resolve the SINGLETON at each
        # use so tests can install one after the daemon started (the
        # reference's injectors are resolved per-call the same way).
        self._fixed_injector = fault_injector
        reg = metrics_system().source(f"datanode.xceiver.{self.port}")
        self._m_writes = reg.counter("blocks_written")
        self._m_reads = reg.counter("blocks_read")
        self._m_bytes_in = reg.counter("bytes_written")
        self._m_bytes_out = reg.counter("bytes_read")
        self._m_short_circuit = reg.counter("short_circuit_grants")
        # log-bucketed op-latency histograms (the /prom exposition's
        # native shape; /jmx sees count/sum/mean of the same series)
        self._m_read_hist = reg.histogram(
            "read_block_seconds", "whole READ_BLOCK op wall time")
        self._m_write_hist = reg.histogram(
            "write_block_seconds", "whole WRITE_BLOCK op wall time")
        # slow-node evidence (ref: DataNodePeerMetrics): rolling
        # per-downstream-peer pipeline ack latency + windowed own
        # service times, published at /ws/v1/peers for the fleet doctor
        from hadoop_tpu.obs.peers import PeerLatencyTracker
        self.peer_tracker = PeerLatencyTracker()
        self._tracer = global_tracer()

    def _fi(self):
        if self._fixed_injector is not None:
            return self._fixed_injector
        from hadoop_tpu.dfs.datanode.datanode import DataNodeFaultInjector
        return DataNodeFaultInjector.get()

    def start(self) -> None:
        self._running = True
        Daemon(self._accept_loop, f"xceiver-server-{self.port}").start()

    def stop(self) -> None:
        self._running = False
        try:
            self._lsock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, addr = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            Daemon(self._serve, f"xceiver-{addr[1]}", args=(sock,)).start()

    def _dial_security(self):
        """Explicit security for DN→DN dials: this DN's own keys when
        secured, else the process default (covers an in-process
        minicluster where the client installed it)."""
        if self.security_keys is None:
            return dt.default_security()
        return dt.TransferSecurity(self.security_keys.newest,
                                   qop=self.required_qop)

    def _serve(self, sock: socket.socket) -> None:
        self.active_xceivers += 1
        try:
            if self.security_keys is not None:
                from hadoop_tpu.security.ugi import AccessControlError
                try:
                    sock = dt.secure_accept(sock, self.security_keys,
                                            self.required_qop)
                except AccessControlError as e:
                    log.warning("xceiver rejected peer: %s", e)
                    return
            req = dt.recv_frame(sock)
            op = req.get("op")
            # Block access tokens gate EVERY op that names a block (ref:
            # DataXceiver.checkAccess before readBlock/writeBlock/
            # copyBlock) — not just the short-circuit grant, or the TCP
            # fallback would bypass the whole scheme.
            if self.block_tokens is not None and op in (
                    dt.OP_WRITE_BLOCK, dt.OP_READ_BLOCK,
                    dt.OP_TRANSFER_BLOCK):
                from hadoop_tpu.dfs.protocol import blocktoken as bt
                from hadoop_tpu.security.ugi import AccessControlError
                mode = {dt.OP_READ_BLOCK: bt.MODE_READ,
                        dt.OP_WRITE_BLOCK: bt.MODE_WRITE,
                        dt.OP_TRANSFER_BLOCK: bt.MODE_COPY}[op]
                bid = Block.from_wire(req["b"]).block_id
                try:
                    try:
                        self.block_tokens.check_access(req.get("tok"),
                                                       bid, mode)
                    except AccessControlError:
                        # striped units carry unit ids; the NN mints one
                        # token per GROUP (ref: LocatedStripedBlock's
                        # per-group token semantics here)
                        from hadoop_tpu.io import erasurecode as ecmod
                        if not ecmod.is_striped_id(bid):
                            raise
                        self.block_tokens.check_access(
                            req.get("tok"), ecmod.group_id_of(bid), mode)
                except AccessControlError as e:
                    dt.send_frame(sock, {"ok": False, "em": str(e),
                                         "denied": True})
                    return
            # Resume the CLIENT's span around the whole op (ref: the
            # HTrace spans DataXceiver opened from the op header). Like
            # the RPC server, no context → no span: sampling is decided
            # at the client root and untraced bulk transfers stay free.
            span_ctx = SpanContext.from_wire(req.get("t"))
            cm = (self._tracer.span(f"dfs.xceiver.{op}", parent=span_ctx)
                  if span_ctx is not None else contextlib.nullcontext())
            t0 = time.monotonic()
            with cm as sp:
                if sp is not None and "b" in req:
                    sp.add_kv("block", str(req["b"].get("id")))
                    sp.add_kv("port", str(self.port))
                # record latency on EVERY edge, not just success — the
                # failed/aborted ops (client died, checksum, mirror
                # failure) are exactly the slow tail the histograms
                # exist to expose
                try:
                    if op == dt.OP_WRITE_BLOCK:
                        self._write_block(sock, req)
                    elif op == dt.OP_READ_BLOCK:
                        self._read_block(sock, req)
                    elif op == dt.OP_TRANSFER_BLOCK:
                        self._transfer_block(sock, req)
                    elif op == dt.OP_SHORT_CIRCUIT:
                        self._short_circuit(sock, req)
                    else:
                        dt.send_frame(sock, {"ok": False,
                                             "em": f"bad op {op!r}"})
                finally:
                    elapsed = time.monotonic() - t0
                    if op == dt.OP_WRITE_BLOCK:
                        self._m_write_hist.add(elapsed)
                        self.peer_tracker.record_self_write(elapsed)
                    elif op == dt.OP_READ_BLOCK:
                        self._m_read_hist.add(elapsed)
                        self.peer_tracker.record_self_read(elapsed)
        except (OSError, EOFError) as e:
            log.debug("xceiver connection error: %s", e)
        except Exception:
            log.exception("xceiver failure")
        finally:
            self.active_xceivers -= 1
            try:
                sock.close()
            except OSError:
                pass

    # -------------------------------------------------------------- writing

    def _write_block(self, up: socket.socket, req: dict) -> None:
        """Ref: DataXceiver.writeBlock:667 + BlockReceiver.receiveBlock:953."""
        block = Block.from_wire(req["b"])
        targets = [DatanodeInfo.from_wire(t) for t in req.get("targets", [])]
        checksum = DataChecksum(req.get("bpc", dt.CHUNK_SIZE))
        self._fi().before_write_block(block)
        xsp = current_span()   # resumed client span (see _serve)
        if xsp is not None:
            # pipeline hop: how many DNs remain DOWNSTREAM of this one
            xsp.add_kv("pipeline_remaining", str(len(targets)))

        down: Optional[socket.socket] = None
        down_name = ""
        if targets:
            nxt, rest = targets[0], targets[1:]
            try:
                down = dt.connect(nxt.xfer_addr(),
                                  security=self._dial_security())
                fwd = dict(req)
                fwd["targets"] = [t.to_wire() for t in rest]
                dt.send_frame(down, fwd)
                setup = dt.recv_frame(down)
                if not setup.get("ok"):
                    raise IOError(
                        f"downstream {nxt} setup failed: {setup.get('em')}")
                down_name = f"{nxt.host}:{nxt.xfer_port}"
            except (OSError, EOFError, IOError) as e:
                # Setup failure: tell upstream which node failed so the client
                # can exclude it (ref: writeBlock's firstBadLink reply).
                dt.send_frame(up, {"ok": False,
                                   "em": f"mirror {nxt} failed: {e}",
                                   "bad_node": nxt.uuid})
                if down is not None:
                    down.close()
                return

        try:
            open_rep = self.store.create_rbw(block, checksum)
        except IOError as e:
            # A re-replication push can race an unreported local replica
            # (IBR lag): tell the sender EXPLICITLY instead of dying with
            # a bare close it retries against forever, and re-announce
            # the replica so the NN stops scheduling the transfer (ref:
            # ReplicaAlreadyExistsException + the IBR that follows).
            already = "already finalized" in str(e)
            if already and req.get("stage") == dt.STAGE_TRANSFER:
                self.on_block_received(block)
            dt.send_frame(up, {"ok": False, "em": str(e),
                               "already": already})
            if down is not None:
                down.close()
            return
        dt.send_frame(up, {"ok": True})

        # Responder: relays downstream acks upstream with our status first.
        # Terminal node acks directly. Ref: BlockReceiver.PacketResponder.
        ack_lock = threading.Lock()
        my_status: dict = {}
        sent_at: dict = {}       # seq -> forward time; guarded-by: ack_lock
        down_uuid = targets[0].uuid if targets else ""
        responder_done = threading.Event()

        def responder():
            try:
                while True:
                    ack = dt.recv_frame(down)
                    now = time.monotonic()
                    with ack_lock:
                        st = my_status.pop(ack["seq"], dt.STATUS_SUCCESS)
                        fwd_t = sent_at.pop(ack["seq"], None)
                    if fwd_t is not None:
                        # forward + downstream ack round trip for THIS
                        # peer: the per-peer signal the doctor's
                        # median/MAD pass runs across (ref: the
                        # SendPacketDownstream timing SlowPeerTracker
                        # aggregates)
                        self.peer_tracker.record(down_uuid, now - fwd_t)
                    dt.send_frame(up, {"seq": ack["seq"],
                                       "statuses": [st] + ack["statuses"],
                                       "last": ack.get("last", False)})
                    if ack.get("last"):
                        return
            except (OSError, EOFError):
                pass
            finally:
                responder_done.set()

        if down is not None:
            Daemon(responder, "packet-responder").start()

        import struct as _struct

        from hadoop_tpu.io.wire import read_frame_buffer, unpack

        ok = True
        try:
            while True:
                # keep the raw frame BUFFER: a mirror forwards it
                # verbatim (no re-encode of the megabyte payload per
                # hop), and receiving into a reusable buffer skips the
                # immutable-bytes copy each hop used to pay
                raw = read_frame_buffer(up)
                pkt = unpack(raw)
                if not isinstance(pkt, dict):
                    raise IOError("malformed packet frame")
                data, sums = pkt.get("data", b""), pkt.get("sums", b"")
                status = dt.STATUS_SUCCESS
                if data:
                    # Verify at the TERMINAL node only — exactly the
                    # reference's rule (BlockReceiver.shouldVerifyChecksum:
                    # mirror nodes forward unverified; the last node's
                    # verdict covers the wire for the whole chain and the
                    # ack path reports which hop corrupted).
                    if down is None:
                        try:
                            checksum.verify(data, sums,
                                            base_pos=pkt.get("off", 0))
                        except ChecksumError as e:
                            log.warning(
                                "Checksum error on %s from upstream: %s",
                                block, e)
                            status = dt.STATUS_ERROR_CHECKSUM
                            ok = False
                    self._fi().before_packet_write(block, pkt)
                    if status == dt.STATUS_SUCCESS:
                        open_rep.write_packet(data, sums)
                        self._m_bytes_in.incr(len(data))
                if down is not None:
                    with ack_lock:
                        my_status[pkt["seq"]] = status
                        sent_at[pkt["seq"]] = time.monotonic()
                    # two sends, zero copies: the old prefix+payload
                    # concatenation copied the whole packet per hop
                    down.sendall(_struct.pack(">I", len(raw)))
                    down.sendall(raw)
                else:
                    dt.send_frame(up, {"seq": pkt["seq"], "statuses": [status],
                                       "last": pkt.get("last", False)})
                if status == dt.STATUS_ERROR_CHECKSUM:
                    # The ack above carries the verdict; tear down NOW.
                    # Accepting later packets would append them after the
                    # missing one — a silent mid-replica hole whose
                    # recomputed CRCs verify — and a client crash would
                    # leave that holed rbw for recovery to finalize. The
                    # client rebuilds the pipeline from the acked prefix.
                    break
                if pkt.get("last"):
                    break
            if xsp is not None:
                xsp.add_kv("bytes", str(open_rep.num_bytes))
                xsp.add_kv("crc_ok", str(ok).lower())
            if ok:
                block.num_bytes = open_rep.num_bytes
                rep = self.store.finalize(open_rep)
                self._m_writes.incr()
                self.on_block_received(rep.to_block())
            else:
                open_rep.abort()
        except (OSError, EOFError) as e:
            # Writer vanished mid-block. KEEP the partial rbw replica on
            # disk — block recovery may finalize it at this length (the rbw
            # directory exists exactly for this; ref: ReplicaBeingWritten
            # surviving pipeline failure, BlockRecoveryWorker).
            log.debug("write of %s interrupted: %s (rbw retained, %d bytes)",
                      block, e, open_rep.num_bytes)
            try:
                open_rep.fsync()
            except OSError:
                pass
            open_rep.close()
        finally:
            if down is not None:
                responder_done.wait(timeout=5.0)
                down.close()

    def _transfer_block(self, sock: socket.socket, req: dict) -> None:
        """Balancer/mover-commanded copy: push a local finalized replica
        to the given targets (ref: DataXceiver.replaceBlock's role — the
        receiving side of Dispatcher.PendingMove, driven here from the
        source)."""
        block = Block.from_wire(req["b"])
        targets = [DatanodeInfo.from_wire(t) for t in req.get("targets", [])]
        try:
            push_block(self.store, block, targets,
                       security=self._dial_security(),
                       block_tokens=self.block_tokens)
        except (OSError, IOError) as e:
            dt.send_frame(sock, {"ok": False, "em": str(e)})
            return
        dt.send_frame(sock, {"ok": True})

    # -------------------------------------------------------------- reading

    def _short_circuit(self, sock: socket.socket, req: dict) -> None:
        """Short-circuit DISCOVERY only: point the client at the DN's
        AF_UNIX fd-passing socket (ref: DataXceiver.requestShortCircuitFds
        + dfs.domain.socket.path). The old path handoff is gone — a
        client that must authenticate to read over TCP could previously
        open any local replica by path; now possession of the replica
        requires the SCM_RIGHTS grant, which checks the block token
        (see datanode/domainsocket.py)."""
        path = self.domain_socket_path
        if path:
            self._m_short_circuit.incr()
            dt.send_frame(sock, {"ok": False, "domain_socket": path,
                                 "em": "use the domain socket for fds"})
        else:
            dt.send_frame(sock, {
                "ok": False,
                "em": "short-circuit path handoff removed; enable "
                      "dfs.domain.socket.path for fd-passing grants"})

    def _read_block(self, sock: socket.socket, req: dict) -> None:
        """Ref: BlockSender.java — chunk-aligned stream with stored sums."""
        block = Block.from_wire(req["b"])
        offset = req.get("offset", 0)
        length = req.get("length", 1 << 62)
        self._fi().before_read_block(block, self.port)
        bpc = dt.CHUNK_SIZE
        try:
            # Probe EAGERLY — read_chunks is a lazy generator, and a
            # replica-not-found must choose the PROVIDED fallback before
            # the setup reply, not explode mid-stream. The probe result
            # feeds read_chunks so the meta header parses once.
            opened = self.store.open_for_read(block)
            bpc = opened[2].bytes_per_chunk
            chunks = self.store.read_chunks(block, offset, length,
                                            opened=opened)
        except IOError as e:
            chunks = self._provided_chunks(block, offset, length)
            if chunks is None:
                dt.send_frame(sock, {"ok": False, "em": str(e)})
                return
        # The reply carries the replica's stored bytes-per-checksum so
        # readers verify with the WRITER's chunking, not their default
        # (ref: OpReadBlock's ReadOpChecksumInfoProto).
        dt.send_frame(sock, {"ok": True, "bpc": bpc})
        seq = 0
        sent = 0
        for pos, data, sums in chunks:
            data, sums = self._fi().corrupt_read_packet(block, data, sums)
            dt.send_frame(sock, {"seq": seq, "off": pos, "data": data,
                                 "sums": sums, "last": False})
            self._m_bytes_out.incr(len(data))
            sent += len(data)
            seq += 1
        dt.send_frame(sock, {"seq": seq, "off": 0, "data": b"", "sums": b"",
                             "last": True})
        self._m_reads.incr()
        xsp = current_span()   # resumed client span (see _serve)
        if xsp is not None:
            xsp.add_kv("bytes", str(sent))
            xsp.add_kv("offset", str(offset))


    def _provided_chunks(self, block: Block, offset: int, length: int):
        """Serve a PROVIDED block by range-reading the external store
        and computing chunk CRCs on the fly (ref: ProvidedVolumeImpl's
        FileRegion reads — the DN is a caching/streaming proxy for data
        that lives outside the cluster)."""
        now = time.monotonic()
        hit = self._alias_cache.get(block.block_id)
        alias = hit[0] if hit and hit[1] > now else None
        if alias is None and self.alias_resolver is not None:
            try:
                alias = self.alias_resolver(block.block_id)
            except Exception as e:  # noqa: BLE001 — NN transient
                log.debug("alias lookup for blk_%d failed: %s",
                          block.block_id, e)
                alias = None
            if alias:
                # TTL bounds the serve-after-delete window; size cap
                # bounds memory (coarse clear — aliases re-resolve).
                if len(self._alias_cache) >= self.ALIAS_CACHE_MAX:
                    self._alias_cache.clear()
                self._alias_cache[block.block_id] = (
                    alias, now + self.ALIAS_CACHE_TTL)
        if not alias:
            return None
        from hadoop_tpu.fs import FileSystem
        from hadoop_tpu.util.crc import DataChecksum

        def gen():
            checksum = DataChecksum(dt.CHUNK_SIZE)
            bpc = checksum.bytes_per_chunk
            visible = min(block.num_bytes, alias["length"])
            start = (offset // bpc) * bpc
            end = min(visible, offset + length)
            fs = FileSystem.get(alias["uri"])
            try:
                with fs.open(_alias_path(alias["uri"])) as f:
                    pos = start
                    while pos < end:
                        n = min(1024 * 1024, end - pos)
                        n = min(((n + bpc - 1) // bpc) * bpc,
                                visible - pos)
                        if hasattr(f, "pread"):
                            data = f.pread(alias["offset"] + pos, n)
                        else:
                            f.seek(alias["offset"] + pos)
                            data = f.read(n)
                        if not data:
                            break
                        sums = checksum.checksums_for(data)
                        yield pos, data, sums
                        pos += len(data)
            finally:
                fs.close()
        return gen()


def _alias_path(uri: str) -> str:
    from hadoop_tpu.fs.filesystem import Path
    return Path(uri).path


def push_block(store: BlockStore, block: Block,
               targets: List[DatanodeInfo],
               security=None, block_tokens=None) -> None:
    """Re-replication push: stream a local finalized replica into a pipeline
    of targets. Ref: DataNode.DataTransfer (new Sender().writeBlock for
    TRANSFER stage; it mints its own token via the DN's shared keys —
    blockTokenSecretManager.generateToken in DataNode.transferBlock)."""
    if not targets:
        return
    req = {
        "op": dt.OP_WRITE_BLOCK, "b": block.to_wire(),
        "targets": [t.to_wire() for t in targets[1:]],
        "stage": dt.STAGE_TRANSFER, "bpc": dt.CHUNK_SIZE,
    }
    from hadoop_tpu.tracing.tracer import current_context
    ctx = current_context()
    if ctx is not None:
        req["t"] = ctx.to_wire()
    if block_tokens is not None:
        from hadoop_tpu.dfs.protocol import blocktoken as bt
        req["tok"] = block_tokens.generate_token(
            "datanode", block.block_id, (bt.MODE_WRITE,))
    sock = dt.connect(targets[0].xfer_addr(), security=security)
    try:
        dt.send_frame(sock, req)
        setup = dt.recv_frame(sock)
        if not setup.get("ok"):
            if setup.get("already"):
                return  # target already holds the replica — push done
            raise IOError(f"transfer setup failed: {setup.get('em')}")
        seq = 0
        for pos, data, sums in store.read_chunks(block, 0, block.num_bytes):
            dt.send_frame(sock, {"seq": seq, "off": pos, "data": data,
                                 "sums": sums, "last": False})
            seq += 1
        dt.send_frame(sock, {"seq": seq, "off": 0, "data": b"", "sums": b"",
                             "last": True})
        # Drain acks until last.
        while True:
            ack = dt.recv_frame(sock)
            if any(s != dt.STATUS_SUCCESS for s in ack["statuses"]):
                raise IOError(f"transfer ack failure: {ack}")
            if ack.get("last"):
                break
    finally:
        sock.close()
