"""HttpFS — the standalone WebHDFS gateway.

Parity with the reference gateway (ref: hadoop-hdfs-project/
hadoop-hdfs-httpfs — HttpFSServer.java exposing the WebHDFS REST API
from a separate daemon that talks to the NameNode as an ordinary
client, fronted by hadoop-auth's AuthenticationFilter): same
``/webhdfs/v1/<path>?op=…`` surface and JSON shapes as the NN-embedded
face (dfs/webhdfs.py), but served from its own process against any
filesystem URI, with pseudo/token authentication on every request. The
proxy niche: REST access for clients outside the cluster's RPC plane
(firewalled or non-Python), without exposing the NameNode itself.
"""

from __future__ import annotations

import logging
import secrets
from typing import Dict, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.webhdfs import (PREFIX, _status_json,
                                    iter_as_caller)
from hadoop_tpu.fs import FileSystem
from hadoop_tpu.http.server import HttpServer
from hadoop_tpu.security.http_auth import AuthFilter
from hadoop_tpu.service import AbstractService

log = logging.getLogger(__name__)


class HttpFSServer(AbstractService):
    def __init__(self, conf: Configuration, fs_uri: str):
        super().__init__("HttpFSServer")
        self.fs_uri = fs_uri
        self.http: Optional[HttpServer] = None
        self._fs: Optional[FileSystem] = None

    def service_init(self, conf: Configuration) -> None:
        self._fs = FileSystem.get(self.fs_uri, conf)
        self.http = HttpServer(
            conf, ("127.0.0.1", conf.get_int("httpfs.http.port", 0)),
            daemon_name="httpfs")
        # no configured secret → a RANDOM one (ref: RandomSignerSecret
        # Provider): a well-known default would let anyone forge the
        # hadoop.auth cookie for any identity
        secret_s = conf.get("httpfs.authentication.signature.secret", "")
        secret = secret_s.encode() if secret_s else secrets.token_bytes(32)
        filt = AuthFilter(
            secret,
            allow_anonymous=conf.get_bool(
                "httpfs.authentication.simple.anonymous.allowed", False))
        self.http.add_handler(PREFIX, filt.wrap(self._handle))

    def service_start(self) -> None:
        self.http.start()
        log.info("HttpFS on :%d -> %s", self.http.port, self.fs_uri)

    def service_stop(self) -> None:
        if self.http:
            self.http.stop()
        if self._fs:
            self._fs.close()

    @property
    def port(self) -> int:
        return self.http.port

    # ------------------------------------------------------------- handler

    def _handle(self, query: Dict, body: bytes) -> Tuple[int, object]:
        # doAs the AUTHENTICATED caller like the NN-embedded face (ref:
        # HttpFSServer's user resolution) — the gateway's own identity
        # must not stand in for the remote user's on the NN, and the
        # AuthFilter principal outranks any user.name parameter
        from hadoop_tpu.security.http_auth import ugi_for_query
        return ugi_for_query(query).do_as(self._handle_as, query, body)

    def _handle_as(self, query: Dict, body: bytes) -> Tuple[int, object]:
        path = query["__path__"][len(PREFIX):] or "/"
        method = query["__method__"]
        op = query.get("op", "").upper()
        fs = self._fs

        if method == "GET":
            if op == "GETFILESTATUS":
                return 200, {"FileStatus": _status_json(
                    fs.get_file_status(path).to_wire())}
            if op == "LISTSTATUS":
                return 200, {"FileStatuses": {"FileStatus": [
                    _status_json(s.to_wire())
                    for s in fs.list_status(path)]}}
            if op == "GETCONTENTSUMMARY":
                cs = fs.client.content_summary(path) if hasattr(
                    fs, "client") else {"dirs": 0, "files": 0, "length": 0}
                return 200, {"ContentSummary": {
                    "directoryCount": cs["dirs"],
                    "fileCount": cs["files"], "length": cs["length"]}}
            if op == "OPEN":
                offset = int(query.get("offset", 0))
                length = int(query.get("length", -1))
                # authorize EAGERLY (while inside do_as, before the 200
                # goes out): open() itself drives the NameNode's read
                # check (get_block_locations → check_access), and
                # closing immediately avoids a handle that would leak
                # if the client vanished before the body streamed
                fs.open(path).close()

                def stream(path=path, offset=offset, length=length):
                    with fs.open(path) as f:
                        if offset:
                            f.seek(offset)
                        left = length if length >= 0 else None
                        while left is None or left > 0:
                            want = 1 << 20 if left is None \
                                else min(1 << 20, left)
                            data = f.read(want)
                            if not data:
                                break
                            if left is not None:
                                left -= len(data)
                            yield data
                return 200, iter_as_caller(stream())
        elif method == "PUT":
            if op == "MKDIRS":
                return 200, {"boolean": fs.mkdirs(path)}
            if op == "RENAME":
                return 200, {"boolean": fs.rename(
                    path, query["destination"])}
            if op == "CREATE":
                overwrite = query.get("overwrite", "false") == "true"
                with fs.create(path, overwrite=overwrite) as f:
                    if isinstance(body, (bytes, bytearray)):
                        f.write(body)
                    else:  # large upload: bounded reader, chunked copy
                        while True:
                            chunk = body.read(1 << 20)
                            if not chunk:
                                break
                            f.write(chunk)
                return 201, {"boolean": True}
        elif method == "DELETE":
            if op == "DELETE":
                recursive = query.get("recursive", "false") == "true"
                return 200, {"boolean": fs.delete(path,
                                                  recursive=recursive)}
        return 400, {"RemoteException": {
            "exception": "UnsupportedOperationException",
            "message": f"op {op!r} with {method}"}}


def main(argv=None) -> int:
    import argparse
    import signal
    ap = argparse.ArgumentParser(prog="httpfs")
    ap.add_argument("--fs", required=True)
    ap.add_argument("--port", type=int, default=14000)
    args = ap.parse_args(argv)
    conf = Configuration()
    conf.set("httpfs.http.port", str(args.port))
    srv = HttpFSServer(conf, args.fs)
    srv.init(conf)
    srv.start()
    print(f"HttpFS serving on :{srv.port}")
    signal.pause()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
