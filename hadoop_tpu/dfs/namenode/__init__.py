from hadoop_tpu.dfs.namenode.namenode import NameNode

__all__ = ["NameNode"]
