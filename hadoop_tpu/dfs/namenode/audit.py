"""NameNode audit log, failure edge — the RPC-facade auditor.

The audit plane is ``hadoop_tpu.audit`` (fsnamesystem.py): success
lines are emitted by the namespace op call sites themselves
(``log_audit_event`` — ugi/ip/cmd/src/dst/callerContext/status/
trace_id, tab-separated k=v, dynamometer-replayable, rotated with
whatever handlers the deployment attached). What those call sites can
never see is the FAILED edge: an op that raised logs nothing, so the
auditor asking "who hammered the namespace with doomed deletes all
night" has no evidence.

This module closes that edge at the RPC seam: a transparent facade
over ``ClientProtocol`` that lets every successful call pass silently
(its fsn call site already logged) and emits exactly one
``status=failed(ExceptionType)`` line — same logger, same format, cmd
named by the RPC method — when the call raises. ``allowed=false`` for
permission denials, the one failure class an auditor reads differently
(ref: FSNamesystem.logAuditEvent's unsuccessful-op calls).

Everything rides the one conf toggle ``namenode.audit.enable``
(default on, like the seed's always-on success lines); off disables
the whole plane and skips installing the facade.
"""

from __future__ import annotations

ENABLE_KEY = "namenode.audit.enable"

# methods whose first (or mapped) string args are the audited paths
_TWO_PATH = {"rename": (0, 1), "rename_snapshot": (0, 2),
             "concat": (0, 1)}
# chatty bookkeeping RPCs whose failures are retry noise, not audit
# signal (lease renewals fire every ~30 s per client)
_SKIP = {"renew_lease", "msync", "get_service_status"}


def _path_args(method: str, args: tuple) -> tuple:
    si, di = _TWO_PATH.get(method, (0, None))
    src = args[si] if len(args) > si and isinstance(args[si], str) \
        else None
    dst = None
    if di is not None and len(args) > di and isinstance(args[di], str):
        dst = args[di]
    return src, dst


class AuditedClientProtocol:
    """Failure-auditing facade: same RPC surface (the server resolves
    methods with ``getattr``, which ``__getattr__`` satisfies), one
    audit line per raising call."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name: str):
        fn = getattr(self._inner, name)
        if name.startswith("_") or not callable(fn):
            return fn

        def audited(*args, **kwargs):
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                _emit_failure(name, args, e)
                raise

        audited.__name__ = name
        # preserve decorator markers (@idempotent) client retry
        # policies introspect
        audited.__dict__.update(getattr(fn, "__dict__", {}))
        # cache so getattr-per-call doesn't rebuild the wrapper
        object.__setattr__(self, name, audited)
        return audited


def _emit_failure(method: str, args: tuple,
                  error: BaseException) -> None:
    if method in _SKIP:
        return
    from hadoop_tpu.dfs.namenode.fsnamesystem import log_audit_event
    src, dst = _path_args(method, args)
    allowed = not isinstance(error, PermissionError)
    log_audit_event(allowed, method, src if src is not None else "null",
                    dst, status=f"failed({type(error).__name__})")


def maybe_audited(proto, conf):
    """Wrap ``proto`` unless ``namenode.audit.enable`` is off."""
    if conf.get_bool(ENABLE_KEY, True):
        return AuditedClientProtocol(proto)
    return proto


# re-exported for callers configuring the plane directly
def audit_enabled(conf) -> bool:
    return conf.get_bool(ENABLE_KEY, True)
