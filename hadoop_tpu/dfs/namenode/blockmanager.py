"""Block → replica map, datanode liveness, replication scheduling, safemode.

Parity with the reference's block management layer (ref:
server/blockmanagement/BlockManager.java (5,459 LoC; :2731 processReport),
DatanodeManager.java (2,052; :1673 handleHeartbeat), HeartbeatManager.java:46,
DatanodeAdminManager.java:78, BlockPlacementPolicyDefault.java):

- ``DatanodeDescriptor`` — server-side view of one block server: stored
  blocks, pending invalidation queue, pending transfer (re-replication) work.
- ``DatanodeManager`` — registration, heartbeats, dead-node sweep,
  decommissioning drains.
- ``BlockManager`` — blocks map keyed by id with expected replication and the
  owning file; full/incremental report processing; under-replication priority
  queues worked off by the RedundancyMonitor; excess-replica pruning; corrupt
  replica tracking; safemode (block threshold + auto-exit).

Replica placement is load-balanced-random over live nodes (the topology seam
exists — ``NetworkTopology`` racks — but one TPU-VM pod is one rack; the
reference's rack spread policy degenerates to spread-over-hosts there).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.protocol.records import (Block, DatanodeInfo, DnCommand,
                                             LocatedBlock)
from hadoop_tpu.io import erasurecode as ec
from hadoop_tpu.metrics import metrics_system
from hadoop_tpu.util.misc import Daemon

log = logging.getLogger(__name__)


class DatanodeDescriptor(DatanodeInfo):
    """NN-side state for one registered datanode.
    Ref: blockmanagement/DatanodeDescriptor.java."""

    __slots__ = ("blocks", "invalidate_queue", "transfer_queue",
                 "recover_queue", "ec_queue", "xceiver_count",
                 "network_location", "cache_queue", "uncache_queue",
                 "cached_blocks")

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.blocks: Set[int] = set()
        self.invalidate_queue: List[Block] = []
        self.transfer_queue: List[Tuple[Block, List[DatanodeInfo]]] = []
        self.recover_queue: List[Tuple[Block, int]] = []
        self.ec_queue: List[Dict] = []  # EC_RECONSTRUCT payloads
        self.xceiver_count = 0
        self.network_location = "/default-pod"
        self.cache_queue: List[Block] = []
        self.uncache_queue: List[Block] = []
        self.cached_blocks: Set[int] = set()

    def public_info(self) -> DatanodeInfo:
        info = DatanodeInfo(self.uuid, self.host, self.xfer_port,
                            self.ipc_port, self.capacity, self.dfs_used,
                            self.remaining, self.storage_type,
                            info_port=self.info_port)
        info.state = self.state
        info.num_blocks = len(self.blocks)
        return info


class BlockInfo:
    """Ref: blockmanagement/BlockInfo.java — block + owning file + replicas."""

    __slots__ = ("block", "inode", "expected_replication", "locations",
                 "corrupt_replicas", "under_construction", "rbw_locations")

    def __init__(self, block: Block, inode, expected_replication: int):
        self.block = block
        self.inode = inode  # INodeFile back-reference (BlockCollection)
        self.expected_replication = expected_replication
        self.locations: Set[str] = set()       # datanode uuids (finalized)
        self.corrupt_replicas: Set[str] = set()
        self.under_construction = True
        # Expected pipeline members while under construction — where rbw
        # replicas live, the targets block recovery contacts.
        # Ref: BlockUnderConstructionFeature.expectedLocations.
        self.rbw_locations: Set[str] = set()

    def live_replicas(self) -> int:
        return len(self.locations - self.corrupt_replicas)


class BlockInfoStriped(BlockInfo):
    """A striped block group (ref: blockmanagement/BlockInfoStriped.java):
    k+m storage units, each a single replica on one DN; ``unit_map`` maps
    datanode uuid → unit index. ``expected_replication`` is k+m."""

    __slots__ = ("policy", "unit_map", "unit_lengths")

    def __init__(self, block: Block, inode, policy: ec.ECPolicy):
        super().__init__(block, inode, policy.num_units)
        self.policy = policy
        self.unit_map: Dict[str, int] = {}
        # Reported finalized unit lengths (idx → bytes), the ground truth
        # for recovering the group's logical length after a client crash.
        self.unit_lengths: Dict[int, int] = {}

    def live_units(self) -> Set[int]:
        return {idx for uuid, idx in self.unit_map.items()
                if uuid in self.locations and
                uuid not in self.corrupt_replicas}

    def live_replicas(self) -> int:
        # "Replicas" for health purposes = distinct live units.
        return len(self.live_units())

    def missing_units(self) -> List[int]:
        live = self.live_units()
        return [i for i in range(self.policy.num_units) if i not in live]

    def logical_length(self) -> int:
        """Data bytes implied by the reported data-unit lengths (ref:
        StripedBlockUtil.getSpannedSize's inverse)."""
        return sum(self.unit_lengths.get(i, 0) for i in range(self.policy.k))


class DatanodeManager:
    """Ref: blockmanagement/DatanodeManager.java."""

    def __init__(self, conf: Configuration, block_manager: "BlockManager"):
        self.conf = conf
        self.bm = block_manager
        self.heartbeat_interval_s = conf.get_time_seconds(
            "dfs.heartbeat.interval", 3.0)
        # Ref formula: 2 * recheck + 10 * heartbeat
        self.dead_interval_s = conf.get_time_seconds(
            "dfs.namenode.heartbeat.recheck-interval", 10.0) * 2 \
            + 10 * self.heartbeat_interval_s
        self._nodes: Dict[str, DatanodeDescriptor] = {}  # guarded-by: _lock
        # uuid -> monotonic expiry: DNs the fleet doctor flagged as
        # statistical outliers (report_slow_peers). Placement treats
        # them as last-resort targets until the TTL lapses — a doctor
        # outage fails OPEN (flags decay, placement heals itself).
        # Ref: SlowPeerTracker feeding BlockPlacementPolicyDefault's
        # excludeSlowNodesEnabled path.
        self._slow_nodes: Dict[str, float] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        # Locality tree (ref: DatanodeManager's NetworkTopology + the
        # dnsToSwitchMapping resolver chain)
        from hadoop_tpu.net import NetworkTopology, TopologyResolver
        self.topology = NetworkTopology(TopologyResolver(conf))

    # ---------------------------------------------------------- registration

    def register(self, info: DatanodeInfo) -> DatanodeDescriptor:
        location = self.topology.add(info.host)
        with self._lock:
            node = self._nodes.get(info.uuid)
            if node is None:
                node = DatanodeDescriptor(info.uuid, info.host,
                                          info.xfer_port, info.ipc_port)
                self._nodes[info.uuid] = node
                log.info("Registered datanode %s at %s", node, location)
            node.network_location = location
            node.host = info.host
            node.xfer_port = info.xfer_port
            node.ipc_port = info.ipc_port
            node.info_port = info.info_port
            node.storage_type = info.storage_type
            # Re-registration revives a DEAD node but must NOT cancel an
            # operator-set admin state — rebooting a DN is exactly what
            # maintenance mode exists for (ref: the admin-state survival
            # in registerDatanode/startAdminOperationIfNecessary).
            if node.state == DatanodeInfo.STATE_DEAD:
                node.state = DatanodeInfo.STATE_LIVE
            node.last_heartbeat = time.monotonic()
            return node

    def get(self, uuid: str) -> Optional[DatanodeDescriptor]:
        with self._lock:
            return self._nodes.get(uuid)

    def handle_heartbeat(self, uuid: str, capacity: int, dfs_used: int,
                         remaining: int, xceivers: int,
                         issue_commands: bool = True) -> List[DnCommand]:
        """Ref: DatanodeManager.handleHeartbeat:1673 — refresh stats, hand the
        node its queued work as commands. A standby NN passes
        ``issue_commands=False``: liveness/stats refresh only, queues stay
        intact for whoever becomes active."""
        with self._lock:
            node = self._nodes.get(uuid)
            if node is None:
                return [DnCommand(DnCommand.REREGISTER)]
            node.last_heartbeat = time.monotonic()
            if node.state == DatanodeInfo.STATE_DEAD:
                # Back from the dead: node_died() already purged its
                # replica map, so a silent revival would leave its
                # blocks location-less until the next periodic report.
                # Command a re-registration — the DN responds with an
                # immediate full block report (ref: handleHeartbeat's
                # unregistered-node path returning DNA_REGISTER).
                node.state = DatanodeInfo.STATE_LIVE
                return [DnCommand(DnCommand.REREGISTER)]
            node.capacity = capacity
            node.dfs_used = dfs_used
            node.remaining = remaining
            node.xceiver_count = xceivers
            if not issue_commands:
                return []
            cmds: List[DnCommand] = []
            if node.invalidate_queue:
                cmds.append(DnCommand(DnCommand.INVALIDATE,
                                      blocks=node.invalidate_queue[:100]))
                del node.invalidate_queue[:100]
            if node.transfer_queue:
                work = node.transfer_queue[:10]
                del node.transfer_queue[:10]
                cmds.append(DnCommand(
                    DnCommand.TRANSFER,
                    blocks=[b for b, _ in work],
                    targets=[t for _, t in work]))
            if node.cache_queue:
                cmds.append(DnCommand(DnCommand.CACHE,
                                      blocks=node.cache_queue[:32]))
                del node.cache_queue[:32]
            if node.uncache_queue:
                cmds.append(DnCommand(DnCommand.UNCACHE,
                                      blocks=node.uncache_queue[:32]))
                del node.uncache_queue[:32]
            if node.recover_queue:
                work = node.recover_queue[:10]
                del node.recover_queue[:10]
                cmds.append(DnCommand(
                    DnCommand.RECOVER,
                    blocks=[b for b, _ in work],
                    new_gen_stamps=[gs for _, gs in work]))
            for payload in node.ec_queue[:4]:
                cmds.append(DnCommand(DnCommand.EC_RECONSTRUCT,
                                      extra=payload))
            del node.ec_queue[:4]
            return cmds

    # ------------------------------------------------------------- liveness

    def check_dead_nodes(self) -> List[DatanodeDescriptor]:
        """Mark nodes past the dead interval; returns newly-dead nodes.
        Ref: HeartbeatManager.heartbeatCheck."""
        now = time.monotonic()
        newly_dead = []
        with self._lock:
            for node in self._nodes.values():
                if (node.state == DatanodeInfo.STATE_LIVE
                        and now - node.last_heartbeat > self.dead_interval_s):
                    node.state = DatanodeInfo.STATE_DEAD
                    newly_dead.append(node)
        for node in newly_dead:
            log.warning("Datanode %s declared dead (no heartbeat for %.1fs)",
                        node, self.dead_interval_s)
        return newly_dead

    def live_nodes(self) -> List[DatanodeDescriptor]:
        with self._lock:
            return [n for n in self._nodes.values()
                    if n.state == DatanodeInfo.STATE_LIVE]

    def all_nodes(self) -> List[DatanodeDescriptor]:
        with self._lock:
            return list(self._nodes.values())

    def start_decommission(self, uuid: str) -> None:
        """Ref: DatanodeAdminManager.startDecommission:78."""
        with self._lock:
            node = self._nodes.get(uuid)
        if node is not None and node.state == DatanodeInfo.STATE_LIVE:
            node.state = DatanodeInfo.STATE_DECOMMISSIONING
            log.info("Starting decommission of %s", node)
            self.bm.schedule_drain(node)

    def start_maintenance(self, uuid: str) -> None:
        """Ref: DatanodeAdminManager.startMaintenance — like decommission
        but the node is expected back; replicas are topped up elsewhere
        without invalidating its copies."""
        with self._lock:
            node = self._nodes.get(uuid)
        if node is not None and node.state == DatanodeInfo.STATE_LIVE:
            node.state = DatanodeInfo.STATE_ENTERING_MAINTENANCE
            log.info("Starting maintenance of %s", node)
            self.bm.schedule_drain(node)

    def stop_maintenance(self, uuid: str) -> None:
        with self._lock:
            node = self._nodes.get(uuid)
            if node is not None and node.state in (
                    DatanodeInfo.STATE_ENTERING_MAINTENANCE,
                    DatanodeInfo.STATE_IN_MAINTENANCE):
                node.state = DatanodeInfo.STATE_LIVE

    def check_admin_progress(self) -> None:
        """Promote DECOMMISSIONING → DECOMMISSIONED (and entering →
        in-maintenance) once every block on the node is adequately
        redundant elsewhere. Ref: DatanodeAdminManager.Monitor.check."""
        with self._lock:
            draining = [n for n in self._nodes.values() if n.state in
                        (DatanodeInfo.STATE_DECOMMISSIONING,
                         DatanodeInfo.STATE_ENTERING_MAINTENANCE)]
        for node in draining:
            drained = self.bm.is_node_drained(node)  # slow — outside lock
            with self._lock:
                # Re-check: an operator may have flipped the state while
                # the drain scan ran (stop_maintenance races this monitor).
                if not drained:
                    continue
                if node.state == DatanodeInfo.STATE_DECOMMISSIONING:
                    node.state = DatanodeInfo.STATE_DECOMMISSIONED
                elif node.state == DatanodeInfo.STATE_ENTERING_MAINTENANCE:
                    node.state = DatanodeInfo.STATE_IN_MAINTENANCE
                else:
                    continue
            log.info("Node %s is now %s", node, node.state)

    # ------------------------------------------------------------ slow nodes

    def set_slow_nodes(self, uuids: List[str], ttl_s: float) -> None:
        """Replace-and-arm: the doctor's CURRENT flagged set, each entry
        expiring after ``ttl_s``. A node the doctor stopped flagging is
        cleared immediately (the push is a full report, not a delta)."""
        deadline = time.monotonic() + max(0.0, ttl_s)
        with self._lock:
            self._slow_nodes = {u: deadline for u in uuids}
        if uuids:
            log.info("placement deprioritizing slow datanodes: %s",
                     [u[:8] for u in uuids])

    def slow_node_uuids(self) -> Set[str]:
        now = time.monotonic()
        with self._lock:
            expired = [u for u, t in self._slow_nodes.items() if t < now]
            for u in expired:
                del self._slow_nodes[u]
            return set(self._slow_nodes)

    # ------------------------------------------------------------ placement

    def choose_targets(self, n: int, exclude: Set[str],
                       writer_host: Optional[str] = None,
                       preferred_types: Optional[List[str]] = None
                       ) -> List[DatanodeDescriptor]:
        """Topology-aware target choice, the reference default policy's
        shape (ref: BlockPlacementPolicyDefault.chooseTarget): replica 1
        on the writer's host when possible; replica 2 OFF the first
        replica's pod (survives a pod/ICI-domain loss); replica 3 on the
        SAME pod as replica 2 (one cross-pod transfer, not two); the rest
        load-spread random. Within each constraint the less-loaded of two
        random candidates wins (power-of-two-choices).
        ``preferred_types`` narrows to those storage types when any such
        node is live (falling back to all, like the reference's
        fallback-storage-type chain)."""
        with self._lock:
            candidates = [node for node in self._nodes.values()
                          if node.state == DatanodeInfo.STATE_LIVE
                          and node.uuid not in exclude]
        if preferred_types:
            typed = [c for c in candidates
                     if c.storage_type in preferred_types]
            if typed:
                candidates = typed
        if not candidates:
            return []
        chosen: List[DatanodeDescriptor] = []
        # doctor-flagged nodes are LAST-RESORT targets: every pick
        # prefers the healthy subset of its pool and falls back to the
        # whole pool only when the constraint can't otherwise be met —
        # a mostly-flagged cluster still places n replicas.
        slow = self.slow_node_uuids()

        def pick_from(pool: List[DatanodeDescriptor]) -> None:
            healthy = [c for c in pool if c.uuid not in slow]
            pool = healthy or pool
            a = random.choice(pool)
            b = random.choice(pool)
            pick = a if a.xceiver_count <= b.xceiver_count else b
            chosen.append(pick)
            candidates.remove(pick)

        # replica 1: writer-local when possible (short-circuit win)
        if writer_host is not None:
            local = [c for c in candidates if c.host == writer_host]
            local = [c for c in local if c.uuid not in slow] or local
            if local:
                pick = min(local, key=lambda c: c.xceiver_count)
                chosen.append(pick)
                candidates.remove(pick)
        if candidates and len(chosen) < n and not chosen:
            pick_from(candidates)
        # replica 2: off the first replica's pod when the cluster spans pods
        if candidates and len(chosen) < n:
            first_pod = chosen[0].network_location
            off_pod = [c for c in candidates
                       if c.network_location != first_pod]
            pick_from(off_pod or candidates)
        # replica 3: same pod as replica 2 (one cross-pod hop total)
        if candidates and len(chosen) < n and len(chosen) >= 2:
            second_pod = chosen[1].network_location
            same = [c for c in candidates
                    if c.network_location == second_pod]
            pick_from(same or candidates)
        while candidates and len(chosen) < n:
            pick_from(candidates)
        return chosen

    def sort_by_distance(self, reader_host: Optional[str],
                         nodes: List[DatanodeDescriptor]
                         ) -> List[DatanodeDescriptor]:
        """Read ordering: local, then same-pod, then the rest (ref:
        DatanodeManager.sortLocatedBlocks → NetworkTopology
        .sortByDistance)."""
        if not reader_host:
            return nodes
        return self.topology.sort_by_distance(reader_host, nodes)


class BlockManager:
    """Ref: blockmanagement/BlockManager.java."""

    def __init__(self, conf: Configuration):
        self.conf = conf
        self.min_replication = conf.get_int("dfs.namenode.replication.min", 1)
        self.max_replication = conf.get_int("dfs.replication.max", 512)
        self.dn_manager = DatanodeManager(conf, self)
        self._blocks: Dict[int, BlockInfo] = {}  # guarded-by: _lock
        self._lock = threading.RLock()
        # Under-replication priority queues (ref: LowRedundancyBlocks.java):
        # 0 = highest risk (1 replica), 1 = under-replicated, 2 = queued drains.
        self._needed: List[Set[int]] = [set(), set(), set()]
        self._pending_reconstruction: Dict[int, float] = {}  # id → deadline
        # Standby postponement (ref: BlockManager.PendingDataNodeMessages
        # + shouldPostponeBlocksFromFuture): a standby's editlog tail can
        # lag the DNs' incremental reports, so a received-report for a
        # block the namespace doesn't know yet must be QUEUED, not
        # invalidated — invalidating would delete the only replica of a
        # just-written block after failover. Replayed when the block
        # appears (edit tailing) and drained on transition to active.
        self.postpone_unknown = False
        self._postponed: Dict[int, List[tuple]] = {}  # id → [(Block, uuid)]
        self._postponed_count = 0
        self.POSTPONED_MAX = 100_000
        # How long a scheduled (re)construction may stay outstanding
        # before re-queueing (ref:
        # dfs.namenode.reconstruction.pending.timeout-sec). EC gets 2x:
        # the worker reads k units before writing.
        self._pending_timeout_s = conf.get_time_seconds(
            "dfs.namenode.reconstruction.pending.timeout", 30.0)
        self.safemode = SafeMode(self, conf)
        reg = metrics_system().source("namenode.blocks")
        reg.register_callback_gauge("blocks_total", lambda: len(self._blocks))
        reg.register_callback_gauge(
            "under_replicated", lambda: sum(len(q) for q in self._needed[:2]))
        reg.register_callback_gauge(
            "pending_reconstruction", lambda: len(self._pending_reconstruction))
        self._m_reconstructions = reg.counter("reconstructions_scheduled")

    # ----------------------------------------------------------- block index

    def add_block_collection(self, block: Block, inode,
                             replication: int) -> BlockInfo:
        with self._lock:
            info = BlockInfo(block, inode, replication)
            self._blocks[block.block_id] = info
            replayed = self._replay_postponed_locked(block.block_id)
        if replayed:
            # outside _lock: report_blocks re-enters it via _blocks_safe
            self.safemode.report_blocks()
        return info

    def add_striped_block_collection(self, block: Block, inode,
                                     policy: ec.ECPolicy
                                     ) -> BlockInfoStriped:
        replayed = False
        with self._lock:
            info = BlockInfoStriped(block, inode, policy)
            self._blocks[block.block_id] = info
            # striped units report under unit ids = group id | index —
            # probe the group's width directly instead of scanning the
            # whole postponed dict per group
            width = policy.k + policy.m
            for bid in [block.block_id] + \
                    [block.block_id + i for i in range(width)]:
                replayed |= self._replay_postponed_locked(bid)
        if replayed:
            self.safemode.report_blocks()
        return info

    def _replay_postponed_locked(self, block_id: int) -> bool:
        msgs = self._postponed.pop(block_id, None)
        if not msgs:
            return False
        self._postponed_count -= len(msgs)
        for blk, uuid in msgs:
            node = self.dn_manager.get(uuid)
            if node is not None:
                self._add_stored_block_locked(blk, node)
        return True

    def process_all_postponed(self) -> None:
        """Drain the postponed queue with postponement OFF — run on
        transition to active (ref: processAllPendingDNMessages): by now
        the namespace is fully caught up, so anything still unknown
        really is deletable."""
        with self._lock:
            self.postpone_unknown = False
            pending, self._postponed = self._postponed, {}
            self._postponed_count = 0
            for msgs in pending.values():
                for blk, uuid in msgs:
                    node = self.dn_manager.get(uuid)
                    if node is not None:
                        self._add_stored_block_locked(blk, node)
        if pending:
            self.safemode.report_blocks()

    def _resolve_locked(self, block_id: int) -> Optional[BlockInfo]:  # lint: holds=_lock
        """Map a reported block id to its BlockInfo; a striped unit id
        resolves to its group (ref: BlockManager.getStoredBlock's
        BlockIdManager.convertToStripedID)."""
        info = self._blocks.get(block_id)
        if info is None and ec.is_striped_id(block_id):
            info = self._blocks.get(ec.group_id_of(block_id))
        return info

    def get(self, block_id: int) -> Optional[BlockInfo]:
        with self._lock:
            return self._blocks.get(block_id)

    def remove_block(self, block: Block) -> None:
        """File deleted: forget the block, queue replica invalidation.
        Ref: BlockManager.removeBlock."""
        with self._lock:
            info = self._blocks.pop(block.block_id, None)
            for q in self._needed:
                q.discard(block.block_id)
            self._pending_reconstruction.pop(block.block_id, None)
            # deletion tailed on a standby: postponed reports for this
            # block are moot — free their slots
            stale = self._postponed.pop(block.block_id, None)
            if stale:
                self._postponed_count -= len(stale)
            if info is None:
                return
            # node.blocks mutations stay under bm._lock like every other
            # replica-map touch (process_report iterates node.blocks -
            # reported under this lock; a concurrent discard would blow
            # up that set difference mid-iteration). dn_manager.get only
            # takes the DN-manager lock, which never calls back here.
            for uuid in info.locations:
                node = self.dn_manager.get(uuid)
                if node is None:
                    continue
                if isinstance(info, BlockInfoStriped):
                    idx = info.unit_map.get(uuid, 0)
                    unit = Block(info.block.block_id + idx,
                                 info.block.gen_stamp)
                    node.invalidate_queue.append(unit)
                    node.blocks.discard(unit.block_id)
                else:
                    node.invalidate_queue.append(info.block)
                    node.blocks.discard(block.block_id)

    def num_blocks(self) -> int:
        with self._lock:
            return len(self._blocks)

    # -------------------------------------------------------------- reports

    def process_report(self, uuid: str, blocks: List[Block]) -> None:
        """Full block report: reconcile the DN's replica set with ours.
        Ref: BlockManager.processReport:2731."""
        node = self.dn_manager.get(uuid)
        if node is None:
            return
        reported = {b.block_id: b for b in blocks}
        with self._lock:
            gone = node.blocks - set(reported)
            for bid in gone:
                self._remove_stored_block_locked(bid, node)
            for bid, blk in reported.items():
                self._add_stored_block_locked(blk, node)
        self.safemode.report_blocks()

    def add_stored_block(self, block: Block, uuid: str) -> None:
        """Incremental 'block received' report.
        Ref: BlockManager.addStoredBlock."""
        node = self.dn_manager.get(uuid)
        if node is None:
            return
        with self._lock:
            self._add_stored_block_locked(block, node)
        self.safemode.report_blocks()

    def remove_stored_block(self, block: Block, uuid: str) -> None:
        node = self.dn_manager.get(uuid)
        if node is None:
            return
        with self._lock:
            self._remove_stored_block_locked(block.block_id, node)

    def _add_stored_block_locked(self, block: Block,
                                 node: DatanodeDescriptor) -> None:
        info = self._resolve_locked(block.block_id)
        if info is None:
            if self.postpone_unknown:
                # Past the cap we DROP rather than invalidate: a lost
                # report self-heals at the next full block report, but
                # an invalidate issued from a lagging standby deletes
                # what may be the only replica of a committed block
                # after failover (commands queue on the descriptor and
                # dispatch once active — namenode.py issue_commands).
                if self._postponed_count < self.POSTPONED_MAX:
                    self._postponed.setdefault(block.block_id, []).append(
                        (block, node.uuid))
                    self._postponed_count += 1
                return
            # Replica of a deleted/unknown block → invalidate at the DN.
            node.invalidate_queue.append(block)
            return
        if self.postpone_unknown and \
                block.gen_stamp > info.block.gen_stamp:
            # Replica from the FUTURE relative to our namespace view
            # (pipeline recovery we haven't tailed yet) — same postpone
            # (same drop-past-cap rationale as above).
            if self._postponed_count < self.POSTPONED_MAX:
                self._postponed.setdefault(block.block_id, []).append(
                    (block, node.uuid))
                self._postponed_count += 1
            return
        if block.gen_stamp < info.block.gen_stamp:
            # Stale replica from a failed pipeline — corrupt by definition.
            info.corrupt_replicas.add(node.uuid)
            node.invalidate_queue.append(block)
            return
        if isinstance(info, BlockInfoStriped):
            idx = ec.unit_index_of(block.block_id)
            info.unit_map[node.uuid] = idx
            if block.num_bytes > info.unit_lengths.get(idx, 0):
                info.unit_lengths[idx] = block.num_bytes
        elif block.num_bytes > info.block.num_bytes:
            info.block.num_bytes = block.num_bytes
        info.locations.add(node.uuid)
        info.corrupt_replicas.discard(node.uuid)
        node.blocks.add(block.block_id)
        self._pending_reconstruction.pop(info.block.block_id, None)
        self._update_needed_locked(info)

    def _remove_stored_block_locked(self, block_id: int,
                                    node: DatanodeDescriptor) -> None:
        info = self._resolve_locked(block_id)
        node.blocks.discard(block_id)
        if info is None:
            return
        info.locations.discard(node.uuid)
        info.corrupt_replicas.discard(node.uuid)
        if isinstance(info, BlockInfoStriped):
            info.unit_map.pop(node.uuid, None)
        self._update_needed_locked(info)

    def mark_corrupt(self, block: Block, uuid: str) -> None:
        """Client/scanner found a bad replica. Ref: BlockManager
        .findAndMarkBlockAsCorrupt."""
        node = self.dn_manager.get(uuid)
        with self._lock:
            info = self._resolve_locked(block.block_id)
            if info is None or node is None:
                return
            info.corrupt_replicas.add(uuid)
            # Only invalidate once a healthy replica can replace it.
            if info.live_replicas() > 0:
                node.invalidate_queue.append(info.block)
                info.locations.discard(uuid)
                node.blocks.discard(block.block_id)
            self._update_needed_locked(info)

    # ----------------------------------------------------- replication queue

    def _update_needed_locked(self, info: BlockInfo) -> None:
        live = info.live_replicas()
        bid = info.block.block_id
        for q in self._needed:
            q.discard(bid)
        if info.under_construction:
            return
        if live < info.expected_replication:
            if bid in self._pending_reconstruction:
                return
            # Highest priority: one more loss makes the block unreadable.
            at_risk = live <= (info.policy.k if isinstance(
                info, BlockInfoStriped) else 1)
            self._needed[0 if at_risk else 1].add(bid)
        elif live > info.expected_replication:
            self._process_excess_locked(info)

    def _process_excess_locked(self, info: BlockInfo) -> None:
        """Drop excess replicas, most-loaded node first.
        Ref: BlockManager.processExtraRedundancyBlock."""
        if isinstance(info, BlockInfoStriped):
            return  # units are unique; nothing is "excess"
        nodes = [self.dn_manager.get(u)
                 for u in (info.locations - info.corrupt_replicas)]
        # Only LIVE-state replicas count toward (and are candidates for)
        # excess — copies on draining nodes are already leaving and
        # pruning live ones against them would starve the drain (ref:
        # countNodes' decommissioning vs live split).
        nodes = [n for n in nodes if n is not None
                 and n.state == DatanodeInfo.STATE_LIVE]
        excess = len(nodes) - info.expected_replication
        if excess <= 0:
            return
        # Drop policy-violating replicas first (the mover just created a
        # right-type copy; pruning it instead would undo the migration —
        # ref: the delNodeHint the reference's Dispatcher passes), then
        # most-loaded.
        from hadoop_tpu.dfs.protocol.records import (POLICY_TYPES,
                                                     effective_storage_policy)
        wanted = POLICY_TYPES.get(effective_storage_policy(info.inode),
                                  ["DISK"])
        nodes.sort(key=lambda n: (n.storage_type in wanted, -len(n.blocks)))
        for node in nodes[:excess]:
            node.invalidate_queue.append(info.block)
            info.locations.discard(node.uuid)
            node.blocks.discard(info.block.block_id)

    def schedule_drain(self, node: DatanodeDescriptor) -> None:
        """Queue every block on a decommissioning node for re-replication.
        Striped unit ids resolve to their group."""
        with self._lock:
            for bid in list(node.blocks):
                info = self._resolve_locked(bid)
                if info is not None and not info.under_construction:
                    self._needed[2].add(info.block.block_id)

    def compute_reconstruction_work(self, max_work: int = 64) -> int:
        """RedundancyMonitor pass: assign transfer work to source DNs.
        Ref: BlockManager.computeBlockReconstructionWork."""
        now = time.monotonic()
        scheduled = 0
        with self._lock:
            # Expire pending reconstructions that never completed.
            for bid, deadline in list(self._pending_reconstruction.items()):
                if deadline < now:
                    del self._pending_reconstruction[bid]
                    info = self._blocks.get(bid)
                    if info is not None:
                        self._update_needed_locked(info)
            for q in self._needed:
                for bid in list(q):
                    if scheduled >= max_work:
                        return scheduled
                    info = self._blocks.get(bid)
                    if info is None:
                        q.discard(bid)
                        continue
                    if self._schedule_one_locked(info):
                        q.discard(bid)
                        scheduled += 1
        return scheduled

    def _schedule_one_locked(self, info: BlockInfo) -> bool:
        if isinstance(info, BlockInfoStriped):
            return self._schedule_ec_locked(info)
        live_uuids = info.locations - info.corrupt_replicas
        sources = [self.dn_manager.get(u) for u in live_uuids]
        sources = [s for s in sources if s is not None and s.state in
                   (DatanodeInfo.STATE_LIVE,
                    DatanodeInfo.STATE_DECOMMISSIONING,
                    DatanodeInfo.STATE_ENTERING_MAINTENANCE)]
        if not sources:
            return False  # unrecoverable for now (all replicas lost)
        # Decommission drains count live-elsewhere replicas as deficits too.
        deficit = info.expected_replication - len(
            [s for s in sources if s.state == DatanodeInfo.STATE_LIVE])
        if deficit <= 0:
            return True  # nothing to do (e.g. replicas recovered meanwhile)
        targets = self.dn_manager.choose_targets(
            deficit, exclude=set(info.locations))
        if not targets:
            return False
        src = min(sources, key=lambda s: len(s.transfer_queue))
        src.transfer_queue.append(
            (info.block, [t.public_info() for t in targets]))
        self._pending_reconstruction[info.block.block_id] = (
            time.monotonic() + self._pending_timeout_s)
        self._m_reconstructions.incr()
        return True

    def _schedule_ec_locked(self, info: BlockInfoStriped) -> bool:
        """Schedule reconstruction of missing striped units: the chosen
        target DN reads k surviving units from peers, decodes, and stores
        the missing unit (ref: BlockManager.scheduleReconstruction →
        BlockECReconstructionCommand; worker ErasureCodingWorker.java:47)."""
        # Units whose every holder is leaving (decommissioning) need a new
        # home just like lost ones (ref: DatanodeAdminManager's handling of
        # striped blocks with only decommissioning replicas).
        fully_live: Set[int] = set()
        sources = []
        for uuid, idx in info.unit_map.items():
            if uuid not in info.locations or uuid in info.corrupt_replicas:
                continue
            n = self.dn_manager.get(uuid)
            if n is None or n.state == DatanodeInfo.STATE_DEAD:
                continue
            sources.append((n.public_info().to_wire(), idx))
            if n.state == DatanodeInfo.STATE_LIVE:
                fully_live.add(idx)
        missing = [i for i in range(info.policy.num_units)
                   if i not in fully_live]
        if not missing:
            return True
        if len({idx for _, idx in sources}) < info.policy.k:
            return False  # unrecoverable until more units resurface
        targets = self.dn_manager.choose_targets(
            len(missing), exclude=set(info.unit_map))
        if not targets:
            return False
        for idx, target in zip(missing, targets):
            target.ec_queue.append({
                "group": info.block.to_wire(),
                "policy": info.policy.name,
                "idx": idx,
                "sources": sources,
            })
        self._pending_reconstruction[info.block.block_id] = (
            time.monotonic() + 2 * self._pending_timeout_s)
        self._m_reconstructions.incr()
        return True

    def is_node_drained(self, node: DatanodeDescriptor) -> bool:
        """True when no block on the node still depends on it."""
        n_live = len(self.dn_manager.live_nodes())  # loop-invariant
        with self._lock:
            for bid in list(node.blocks):
                info = self._resolve_locked(bid)
                if info is None or info.under_construction:
                    continue
                others = {u for u in (info.locations - info.corrupt_replicas)
                          if u != node.uuid}
                live_others = [u for u in others
                               if (n := self.dn_manager.get(u)) is not None
                               and n.state == DatanodeInfo.STATE_LIVE]
                if isinstance(info, BlockInfoStriped):
                    unit = ec.unit_index_of(bid)
                    if not any(info.unit_map.get(u) == unit
                               for u in live_others):
                        return False
                elif len(live_others) < min(info.expected_replication,
                                            n_live):
                    return False
            return True

    def blocks_on_node(self, uuid: str, max_blocks: int = 256,
                       min_size: int = 0) -> List[Block]:
        """Blocks stored on a node, biggest first — the balancer's source
        inventory (ref: NamenodeProtocol.getBlocks)."""
        node = self.dn_manager.get(uuid)
        if node is None:
            return []
        out: List[Block] = []
        with self._lock:
            for bid in list(node.blocks):
                info = self._resolve_locked(bid)
                if info is None or info.under_construction or \
                        isinstance(info, BlockInfoStriped):
                    continue  # balancer moves contiguous replicas only
                if info.block.num_bytes >= min_size:
                    out.append(info.block)
        out.sort(key=lambda b: -b.num_bytes)
        return out[:max_blocks]

    def invalidate_replica(self, block: Block, uuid: str) -> bool:
        """Drop one specific replica (mover/balancer cleanup; ref: the
        excess-replica choice the Dispatcher makes via delHints)."""
        node = self.dn_manager.get(uuid)
        with self._lock:
            info = self._resolve_locked(block.block_id)
            if info is None or node is None:
                return False
            if info.live_replicas() <= 1:
                return False  # never drop the last copy
            node.invalidate_queue.append(block)
            info.locations.discard(uuid)
            node.blocks.discard(block.block_id)
            self._update_needed_locked(info)
            return True

    def node_died(self, node: DatanodeDescriptor) -> None:
        """All replicas on a dead node are gone; requeue its blocks."""
        with self._lock:
            for bid in list(node.blocks):
                self._remove_stored_block_locked(bid, node)

    # --------------------------------------------------------------- queries

    def located_block(self, block: Block, offset: int,
                      reader_host: Optional[str] = None) -> LocatedBlock:
        with self._lock:
            info = self._blocks.get(block.block_id)
            if info is None:
                return LocatedBlock(block, [], offset)
            if isinstance(info, BlockInfoStriped):
                locs, indices = [], []
                for uuid in info.locations - info.corrupt_replicas:
                    node = self.dn_manager.get(uuid)
                    if node is not None and \
                            node.state != DatanodeInfo.STATE_DEAD and \
                            uuid in info.unit_map:
                        locs.append(node.public_info())
                        indices.append(info.unit_map[uuid])
                return LocatedBlock(info.block, locs, offset,
                                    corrupt=len(set(indices)) < info.policy.k,
                                    ec_policy=info.policy.name,
                                    indices=indices)
            locs = []
            for uuid in info.locations - info.corrupt_replicas:
                node = self.dn_manager.get(uuid)
                if node is not None and node.state != DatanodeInfo.STATE_DEAD:
                    locs.append(node.public_info())
            random.shuffle(locs)  # spread read load among equals
            if reader_host:
                # closest-first for this reader (ref: DatanodeManager
                # .sortLocatedBlocks); the shuffle above still spreads
                # load within each distance class (sort is stable)
                locs = self.dn_manager.topology.sort_by_distance(
                    reader_host, locs)
            cached = self.cached_holders(info.block.block_id)
            if cached:
                # memory-resident replicas first (ref: cachedLocations)
                locs = sorted(locs,
                              key=lambda d: d.uuid not in cached)
            return LocatedBlock(info.block, locs, offset,
                                corrupt=(not locs and bool(info.locations)),
                                cached_uuids=cached)

    def complete_block(self, block: Block) -> None:
        with self._lock:
            info = self._blocks.get(block.block_id)
            if info is not None:
                info.under_construction = False
                info.block.num_bytes = block.num_bytes
                self._update_needed_locked(info)

    # --------------------------------------------------------------- cache

    def report_cached(self, uuid: str, cached_ids: List[int]) -> None:
        """DN's full cached-set report (ref: DatanodeProtocol
        cacheReport)."""
        node = self.dn_manager.get(uuid)
        if node is not None:
            node.cached_blocks = set(cached_ids)

    def cached_holders(self, block_id: int) -> List[str]:
        with self._lock:
            info = self._blocks.get(block_id)
            if info is None:
                return []
            holders = info.locations - info.corrupt_replicas
        out = []
        for uuid in holders:
            node = self.dn_manager.get(uuid)
            if node is not None and block_id in node.cached_blocks:
                out.append(uuid)
        return out

    def reconcile_cache(self, wanted_block_ids: Set[int]) -> None:
        """CacheReplicationMonitor pass (ref: blockmanagement/
        CacheReplicationMonitor.java): queue CACHE work for directive-
        covered blocks with no cached replica, UNCACHE for cached blocks
        no directive covers."""
        with self._lock:
            want = {bid: self._blocks.get(bid) for bid in wanted_block_ids}
        for bid, info in want.items():
            if info is None:
                continue
            holders = [u for u in (info.locations - info.corrupt_replicas)]
            nodes = [self.dn_manager.get(u) for u in holders]
            nodes = [n for n in nodes
                     if n is not None
                     and n.state == DatanodeInfo.STATE_LIVE]
            if not nodes:
                continue
            if any(bid in n.cached_blocks
                   or any(b.block_id == bid for b in n.cache_queue)
                   for n in nodes):
                continue
            pick = min(nodes, key=lambda n: len(n.cached_blocks))
            pick.cache_queue.append(info.block)
        # uncache anything no directive wants
        for node in list(self.dn_manager._nodes.values()):
            for bid in list(node.cached_blocks):
                if bid not in wanted_block_ids:
                    with self._lock:
                        info = self._blocks.get(bid)
                    if info is not None and not any(
                            b.block_id == bid for b in node.uncache_queue):
                        node.uncache_queue.append(info.block)

    def under_replicated_count(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._needed[:2])


class SafeMode:
    """Startup safemode: reject mutations until enough blocks are reported.
    Ref: blockmanagement/BlockManagerSafeMode.java."""

    def __init__(self, bm: BlockManager, conf: Configuration):
        self.bm = bm
        self.threshold = conf.get_float(
            "dfs.namenode.safemode.threshold-pct", 0.999)
        self.extension_s = conf.get_time_seconds(
            "dfs.namenode.safemode.extension", 0.0)
        self._on = True
        self._manual = False
        self._block_total = 0
        self._reached_at: Optional[float] = None
        self._lock = threading.Lock()

    def set_block_total(self, total: int) -> None:
        with self._lock:
            self._block_total = total
        self.report_blocks()

    def is_on(self) -> bool:
        return self._on

    def enter_manual(self) -> None:
        with self._lock:
            self._on = True
            self._manual = True

    def leave(self, force: bool = False) -> None:
        with self._lock:
            self._on = False
            self._manual = False
        log.info("Safemode is OFF%s", " (forced)" if force else "")

    def _blocks_safe(self) -> int:
        count = 0
        with self.bm._lock:
            for info in self.bm._blocks.values():
                need = info.policy.k if isinstance(info, BlockInfoStriped) \
                    else self.bm.min_replication
                if info.under_construction or info.live_replicas() >= need:
                    count += 1
        return count

    def report_blocks(self) -> None:
        if not self._on or self._manual:
            return
        import math
        with self._lock:
            needed = math.ceil(self.threshold * self._block_total)
            if self._blocks_safe() >= needed:
                if self._reached_at is None:
                    self._reached_at = time.monotonic()
                if time.monotonic() - self._reached_at >= self.extension_s:
                    self._on = False
                    log.info("Safemode is OFF (threshold reached)")
            else:
                self._reached_at = None

    def status(self) -> Dict:
        with self._lock:
            return {"on": self._on, "manual": self._manual,
                    "block_total": self._block_total,
                    "blocks_safe": self._blocks_safe() if self._on else None,
                    "threshold": self.threshold}
