"""Write-ahead edit log with batched group-commit sync.

Parity with the reference's journal (ref: server/namenode/FSEditLog.java
(1,888 LoC), :646/:651 logSync; EditLogFileOutputStream.java,
FSEditLogLoader.java): every namespace mutation appends a transaction under
the namesystem lock, then the caller invokes ``log_sync()`` *outside* the
lock; syncs are batched — one fsync covers every txid appended since the last
sync (the group-commit that makes metadata throughput scale with concurrency).

Storage layout (per journal directory):
    edits_inprogress_<first_txid>      — active segment
    edits_<first_txid>-<last_txid>     — finalized segments
    seen_txid                          — highest txid durably begun

Record format: u32-framed wirepack dicts ``{"t": txid, "op": name, ...}``.
A torn tail (partial frame after crash) is truncated on replay, as the
reference's loader tolerates (FSEditLogLoader recovery mode).

Pluggable JournalManager seam: the default writes one local directory; the
quorum journal (qjournal.py) plugs in here the way QuorumJournalManager does.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

from hadoop_tpu.io.wire import pack, unpack
from hadoop_tpu.metrics import metrics_system

log = logging.getLogger(__name__)

# Edit-log op codes (ref: FSEditLogOpCodes.java)
OP_ADD = "add"                # create file (under construction) + lease
OP_ADD_BLOCK = "add_block"    # allocate next block
OP_UPDATE_BLOCKS = "update_blocks"  # pipeline recovery rewrote block list
OP_CLOSE = "close"            # complete file (finalize blocks + lengths)
OP_MKDIR = "mkdir"
OP_DELETE = "delete"
OP_RENAME = "rename"
OP_SET_REPLICATION = "set_replication"
OP_SET_PERMISSION = "set_permission"
OP_SET_OWNER = "set_owner"
OP_SET_TIMES = "set_times"
OP_SET_QUOTA = "set_quota"
OP_CONCAT = "concat"
OP_TRUNCATE = "truncate"
OP_SYMLINK = "symlink"
OP_REASSIGN_LEASE = "reassign_lease"
OP_SET_GENSTAMP = "set_genstamp"
OP_PROVIDED_FILE = "provided_file"  # fs2img: external file mounted
OP_SET_XATTR = "set_xattr"
OP_REMOVE_XATTR = "remove_xattr"
OP_SET_ACL = "set_acl"
OP_CREATE_SNAPSHOT = "create_snapshot"
OP_DELETE_SNAPSHOT = "delete_snapshot"
OP_RENAME_SNAPSHOT = "rename_snapshot"
OP_ALLOW_SNAPSHOT = "allow_snapshot"
OP_DISALLOW_SNAPSHOT = "disallow_snapshot"
OP_SET_STORAGE_POLICY = "set_storage_policy"
OP_SET_EC_POLICY = "set_ec_policy"
OP_ADD_CACHE_DIRECTIVE = "add_cache_directive"
OP_REMOVE_CACHE_DIRECTIVE = "remove_cache_directive"


class EditLogFaultInjector:
    """Overridable fault point at the edit-log group-commit boundary (ref:
    the reference's injector-singleton pattern — CheckpointFaultInjector
    .java / JournalFaultInjector.java). ``before_sync`` raising simulates
    journal IO failure at exactly the durability point."""

    _instance: "EditLogFaultInjector" = None  # type: ignore[assignment]

    @classmethod
    def get(cls) -> "EditLogFaultInjector":
        if cls._instance is None:
            cls._instance = EditLogFaultInjector()
        return cls._instance

    @classmethod
    def set(cls, inst) -> None:
        cls._instance = inst

    def before_sync(self, txid: int) -> None: ...


class JournalManager:
    """Seam for pluggable journals (local dir / quorum).
    Ref: server/namenode/JournalManager.java."""

    def start_segment(self, first_txid: int) -> None: ...
    def journal(self, records: bytes, first_txid: int, count: int) -> None: ...
    def sync(self) -> None: ...
    def finalize_segment(self, first_txid: int, last_txid: int) -> None: ...
    def discard_inprogress(self, first_txid: int) -> None: ...
    def read_edits(self, from_txid: int) -> Iterator[Dict]: ...
    def write_seen_txid(self, txid: int) -> None: ...
    def read_seen_txid(self) -> int: ...
    def close(self) -> None: ...


class FileJournalManager(JournalManager):
    """One local journal directory. Ref: server/namenode/FileJournalManager.java."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._f = None
        self._inprogress_first: Optional[int] = None

    # ------------------------------------------------------------- writing

    def start_segment(self, first_txid: int) -> None:
        assert self._f is None, "segment already open"
        path = os.path.join(self.dir, f"edits_inprogress_{first_txid}")
        if os.path.exists(path):
            # Crash recovery: a torn partial frame at the tail must be
            # physically truncated before appending, or edits written after
            # it would be unreachable on the next replay (the reader stops
            # at the first bad frame). Ref: EditLogFileOutputStream recovery
            # + FSEditLogLoader recovery mode.
            valid = _valid_prefix_len(path)
            if valid < os.path.getsize(path):
                log.warning("Truncating torn edit segment %s from %d to %d "
                            "bytes", path, os.path.getsize(path), valid)
                with open(path, "r+b") as f:
                    f.truncate(valid)
        self._f = open(path, "ab")
        self._inprogress_first = first_txid

    def journal(self, records: bytes, first_txid: int, count: int) -> None:
        self._f.write(records)

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def finalize_segment(self, first_txid: int, last_txid: int) -> None:
        assert self._inprogress_first == first_txid
        self._f.close()
        self._f = None
        src = os.path.join(self.dir, f"edits_inprogress_{first_txid}")
        dst = os.path.join(self.dir, f"edits_{first_txid}-{last_txid}")
        os.rename(src, dst)
        self._inprogress_first = None

    def discard_inprogress(self, first_txid: int) -> None:
        self.close()
        p = os.path.join(self.dir, f"edits_inprogress_{first_txid}")
        if os.path.exists(p):
            os.remove(p)

    def write_seen_txid(self, txid: int) -> None:
        tmp = os.path.join(self.dir, "seen_txid.tmp")
        with open(tmp, "w") as f:
            f.write(str(txid))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.dir, "seen_txid"))

    def read_seen_txid(self) -> int:
        p = os.path.join(self.dir, "seen_txid")
        if not os.path.exists(p):
            return 0
        with open(p) as f:
            return int(f.read().strip() or 0)

    # ------------------------------------------------------------- reading

    def segments(self) -> List[tuple]:
        """Sorted (first_txid, last_txid_or_None, path)."""
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("edits_inprogress_"):
                out.append((int(name.rsplit("_", 1)[1]), None,
                            os.path.join(self.dir, name)))
            elif name.startswith("edits_") and "-" in name:
                rng = name[len("edits_"):]
                first, last = rng.split("-")
                out.append((int(first), int(last),
                            os.path.join(self.dir, name)))
        return sorted(out)

    def read_edits(self, from_txid: int) -> Iterator[Dict]:
        for first, last, path in self.segments():
            if last is not None and last < from_txid:
                continue
            yield from _read_segment_file(path, from_txid)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def _valid_prefix_len(path: str) -> int:
    """Byte length of the longest prefix of whole, decodable frames."""
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    n = len(data)
    while n - off >= 4:
        (flen,) = struct.unpack_from(">I", data, off)
        if n - off - 4 < flen:
            break
        try:
            unpack(data[off + 4: off + 4 + flen])
        except Exception:
            break
        off += 4 + flen
    return off


def _read_segment_file(path: str, from_txid: int) -> Iterator[Dict]:
    """Frame-by-frame read tolerating a torn tail (crash mid-write)."""
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    n = len(data)
    while n - off >= 4:
        (flen,) = struct.unpack_from(">I", data, off)
        if n - off - 4 < flen:
            break  # torn tail — ignore, as recovery does
        try:
            rec = unpack(data[off + 4: off + 4 + flen])
        except Exception:  # torn/corrupt tail
            break
        off += 4 + flen
        if rec.get("t", 0) >= from_txid:
            yield rec


class FSEditLog:
    """Transaction log with group commit. Ref: FSEditLog.java.

    Usage (mirrors the reference's discipline):
        with namesystem write lock:
            txid = editlog.log_edit(OP_MKDIR, {"path": ...})
        # lock released
        editlog.log_sync(txid)        # batched fsync up to >= txid
    """

    def __init__(self, journal: JournalManager):
        self.journal = journal
        self._lock = threading.Lock()        # append ordering
        self._sync_lock = threading.Lock()   # one syncer at a time
        self._cond = threading.Condition(self._lock)
        self._txid = 0
        self._synced_txid = 0
        self._buf = bytearray()              # appended, not yet written
        self._buf_first_txid: Optional[int] = None
        self._buf_count = 0
        self._segment_first: Optional[int] = None
        self._open = False
        reg = metrics_system().source("namenode.editlog")
        self._m_txns = reg.counter("transactions")
        self._m_syncs = reg.counter("syncs")
        self._m_sync_time = reg.rate("sync")
        self._m_batched = reg.counter("transactions_batched_in_sync")

    # ------------------------------------------------------------ lifecycle

    def open_for_write(self, last_loaded_txid: int) -> None:
        self._txid = last_loaded_txid
        self._synced_txid = last_loaded_txid
        self._segment_first = self._txid + 1
        self.journal.start_segment(self._segment_first)
        self.journal.write_seen_txid(self._txid + 1)
        self._open = True

    def close(self) -> None:
        self.close_segment()
        self.journal.close()

    def close_segment(self) -> None:
        """Flush + finalize the open segment WITHOUT closing the journal
        manager — demotion to standby keeps tailing through the same
        QuorumJournalManager (ref: FSEditLog.close vs. the standby's
        continued use of the shared journal)."""
        if not self._open:
            return
        # _sync_lock serializes against concurrent log_sync; the internal
        # flush covers any buffered edits. A log_edit racing close() would be
        # a namesystem bug (mutations after shutdown), not an editlog one.
        with self._sync_lock:
            last = self._flush_and_sync_locked()
            first = self._segment_first
            self._open = False
            if first is not None and last >= first:
                self.journal.finalize_segment(first, last)

    def roll(self) -> int:
        """Finalize the current segment and start a new one (checkpointing
        boundary). Ref: FSEditLog.rollEditLog. Returns first txid of the new
        segment.

        Holds _sync_lock across flush + finalize + restart so a concurrent
        log_sync can neither write into a closing segment nor observe the
        journal handle mid-swap; the txid boundary is captured atomically
        with the buffer drain, so every txid <= boundary is in the finalized
        segment and every later txid lands in the new one."""
        with self._sync_lock:
            last = self._flush_and_sync_locked()
            first = self._segment_first
            new_first = last + 1
            self._segment_first = new_first
            if last >= first:
                self.journal.finalize_segment(first, last)
            else:
                # Empty in-progress segment: remove and restart.
                self.journal.discard_inprogress(first)
            self.journal.start_segment(new_first)
            self.journal.write_seen_txid(new_first)
            return new_first

    # -------------------------------------------------------------- logging

    @property
    def last_txid(self) -> int:
        return self._txid

    @property
    def synced_txid(self) -> int:
        return self._synced_txid

    def log_edit(self, op: str, payload: Dict[str, Any]) -> int:
        """Append one transaction to the in-memory buffer; returns its txid.
        Called under the namesystem write lock (ordering guarantee)."""
        assert self._open, "edit log not open"
        rec = dict(payload)
        with self._lock:
            self._txid += 1
            rec["t"] = self._txid
            rec["op"] = op
            data = pack(rec)
            self._buf += struct.pack(">I", len(data)) + data
            if self._buf_first_txid is None:
                self._buf_first_txid = self._txid
            self._buf_count += 1
            self._m_txns.incr()
            return self._txid

    def log_sync(self, txid: Optional[int] = None) -> None:
        """Group commit: returns once txid (default: latest) is durable.
        Ref: FSEditLog.logSync:646 — the double-checked batching dance."""
        if txid is None:
            txid = self._txid
        if self._synced_txid >= txid:
            return
        with self._sync_lock:
            # Re-check: a concurrent syncer may have covered us while we
            # waited for the sync lock — that's the batching win.
            if self._synced_txid >= txid:
                return
            self._flush_and_sync_locked()

    def _flush_and_sync_locked(self) -> int:
        """Drain the buffer + fsync. Caller holds _sync_lock. Returns the
        txid boundary covered (atomic with the buffer capture)."""
        EditLogFaultInjector.get().before_sync(self._txid)
        with self._lock:
            buf = bytes(self._buf)
            first = self._buf_first_txid
            count = self._buf_count
            sync_to = self._txid
            self._buf = bytearray()
            self._buf_first_txid = None
            self._buf_count = 0
        if buf:
            self.journal.journal(buf, first, count)
        if self._open:
            with self._m_sync_time.time():
                self.journal.sync()
        self._synced_txid = sync_to
        self._m_syncs.incr()
        if count > 1:
            self._m_batched.incr(count - 1)
        return sync_to
