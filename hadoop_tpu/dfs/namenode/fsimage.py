"""Namespace checkpoint images.

Parity with the reference's fsimage (ref: server/namenode/FSImage.java
(1,562 LoC), FSImageFormatProtobuf.java): the full namespace serialized to
``fsimage_<txid>`` with an MD5 side file; startup loads the newest image then
replays edit segments past its txid (FSNamesystem.loadFromDisk:766). Saving
writes to ``.ckpt`` then renames — a torn save never shadows a good image.
"""

from __future__ import annotations

import hashlib
import logging
import os
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

from hadoop_tpu.dfs.namenode.inodes import (FSDirectory, INode,
                                            INodeDirectory, INodeFile)
from hadoop_tpu.dfs.protocol.records import Block
from hadoop_tpu.io.wire import pack, unpack


def _common_attrs(node: INode, d: Dict) -> Dict:
    if node.xattrs:
        d["xa"] = dict(node.xattrs)
    if node.acl:
        d["acl"] = list(node.acl)
    if node.storage_policy:
        d["sp"] = node.storage_policy
    return d


def _restore_common(node: INode, d: Dict) -> None:
    node.mtime = d.get("mt", 0.0)
    node.group = d.get("g", "")
    node.xattrs = dict(d["xa"]) if d.get("xa") else None
    node.acl = list(d["acl"]) if d.get("acl") else None
    node.storage_policy = d.get("sp")


def _serialize_node(node: INode) -> Dict:
    if isinstance(node, INodeDirectory):
        d = {
            "k": "d", "n": node.name, "mt": node.mtime, "o": node.owner,
            "g": node.group, "pm": node.permission,
            "c": [_serialize_node(c) for c in node.children.values()],
        }
        if node.ec_policy:
            d["ec"] = node.ec_policy
        if node.ns_quota >= 0 or node.space_quota >= 0:
            d["nq"], d["sq"] = node.ns_quota, node.space_quota
        if node.snapshottable:
            d["snap"] = {name: _serialize_node(root)
                         for name, root in (node.snapshots or {}).items()}
        return _common_attrs(node, d)
    f: INodeFile = node  # type: ignore[assignment]
    d = {
        "k": "f", "n": f.name, "mt": f.mtime, "o": f.owner, "g": f.group,
        "pm": f.permission, "rep": f.replication, "bs": f.block_size,
        "uc": f.under_construction, "cl": f.client_name,
        "b": [b.to_wire() for b in f.blocks],
    }
    if f.ec_policy:
        d["ec"] = f.ec_policy
    return _common_attrs(f, d)


def _deserialize_node(d: Dict) -> INode:
    if d["k"] == "d":
        node = INodeDirectory(d["n"], owner=d.get("o", ""),
                              permission=d.get("pm", 0o755))
        _restore_common(node, d)
        node.ec_policy = d.get("ec")
        node.ns_quota = d.get("nq", -1)
        node.space_quota = d.get("sq", -1)
        if "snap" in d:
            node.snapshottable = True
            node.snapshots = {name: _deserialize_node(sd)
                              for name, sd in d["snap"].items()}
        for cd in d.get("c", []):
            node.add_child(_deserialize_node(cd))
        return node
    f = INodeFile(d["n"], d.get("rep", 3), d.get("bs", 0),
                  owner=d.get("o", ""), permission=d.get("pm", 0o644),
                  ec_policy=d.get("ec"))
    _restore_common(f, d)
    f.under_construction = d.get("uc", False)
    f.client_name = d.get("cl")
    f.blocks = [Block.from_wire(b) for b in d.get("b", [])]
    return f


class FSImage:
    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def save(self, fsdir: FSDirectory, txid: int, extra: Dict) -> str:
        """Checkpoint the namespace as of ``txid``. ``extra`` carries counters
        that must survive restart (next block id, generation stamp, leases)."""
        payload = pack({
            "v": 1, "txid": txid, "extra": extra,
            "root": _serialize_node(fsdir.root),
            "inodes": fsdir.num_inodes(),
        })
        digest = hashlib.md5(payload).hexdigest()
        final = os.path.join(self.dir, f"fsimage_{txid:019d}")
        tmp = final + ".ckpt"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        # tmp+rename the side file too: a crash between the image rename
        # and a bare md5 write left a torn .md5 that failed load() hard
        md5_tmp = final + ".md5.tmp"
        with open(md5_tmp, "w") as f:
            f.write(digest)
            f.flush()
            os.fsync(f.fileno())
        os.replace(md5_tmp, final + ".md5")
        return final

    def _images(self) -> List[Tuple[int, str]]:
        out: List[Tuple[int, str]] = []
        for name in os.listdir(self.dir):
            if name.startswith("fsimage_") and not name.endswith(
                    (".md5", ".ckpt", ".tmp")):
                out.append((int(name.split("_", 1)[1]),
                            os.path.join(self.dir, name)))
        return sorted(out)

    def newest_image(self) -> Optional[Tuple[int, str]]:
        images = self._images()
        return images[-1] if images else None

    def load(self) -> Optional[Tuple[int, FSDirectory, Dict]]:
        """Load the newest VERIFIABLE image; returns (txid, fsdir,
        extra) or None. A corrupt/torn newest image falls back to the
        next retained one (the edit log replays the difference) instead
        of refusing to start — ref: FSImage iterating candidate images
        in save-order until one loads."""
        images = self._images()
        if not images:
            return None
        last_err: Optional[Exception] = None
        for txid, path in reversed(images):
            try:
                with open(path, "rb") as f:
                    payload = f.read()
                md5_path = path + ".md5"
                if os.path.exists(md5_path):
                    with open(md5_path) as f:
                        want = f.read().strip()
                    if not want:
                        # empty side file = the pre-atomic-write crash
                        # artifact; treat like a missing one (nothing to
                        # validate against) rather than condemning a
                        # perfectly good image
                        log.warning("fsimage %s has an empty .md5; "
                                    "skipping digest check", path)
                    else:
                        got = hashlib.md5(payload).hexdigest()
                        if want != got:
                            raise IOError(
                                f"fsimage {path} is corrupt (md5 {got} "
                                f"!= recorded {want})")
                d = unpack(payload)
            except Exception as e:  # noqa: BLE001 — try the older image
                log.error("fsimage %s unusable (%s); trying older", path, e)
                last_err = e
                continue
            fsdir = FSDirectory()
            fsdir.root = _deserialize_node(d["root"])  # type: ignore[assignment]
            fsdir._inode_count = d.get("inodes", 1)
            return d["txid"], fsdir, d.get("extra", {})
        raise IOError(f"no loadable fsimage in {self.dir}") from last_err

    def purge_old(self, keep: int = 2) -> None:
        """Retain the newest ``keep`` images. Ref: NNStorageRetentionManager."""
        images: List[Tuple[int, str]] = []
        for name in os.listdir(self.dir):
            if name.startswith("fsimage_") and not name.endswith((".md5", ".ckpt")):
                images.append((int(name.split("_", 1)[1]),
                               os.path.join(self.dir, name)))
        for _, path in sorted(images)[:-keep]:
            os.remove(path)
            if os.path.exists(path + ".md5"):
                os.remove(path + ".md5")
