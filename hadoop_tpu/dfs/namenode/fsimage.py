"""Namespace checkpoint images.

Parity with the reference's fsimage (ref: server/namenode/FSImage.java
(1,562 LoC), FSImageFormatProtobuf.java): the full namespace serialized to
``fsimage_<txid>`` with an MD5 side file; startup loads the newest image then
replays edit segments past its txid (FSNamesystem.loadFromDisk:766). Saving
writes to ``.ckpt`` then renames — a torn save never shadows a good image.
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.dfs.namenode.inodes import (FSDirectory, INode,
                                            INodeDirectory, INodeFile)
from hadoop_tpu.dfs.protocol.records import Block
from hadoop_tpu.io.wire import pack, unpack


def _common_attrs(node: INode, d: Dict) -> Dict:
    if node.xattrs:
        d["xa"] = dict(node.xattrs)
    if node.acl:
        d["acl"] = list(node.acl)
    if node.storage_policy:
        d["sp"] = node.storage_policy
    return d


def _restore_common(node: INode, d: Dict) -> None:
    node.mtime = d.get("mt", 0.0)
    node.group = d.get("g", "")
    node.xattrs = dict(d["xa"]) if d.get("xa") else None
    node.acl = list(d["acl"]) if d.get("acl") else None
    node.storage_policy = d.get("sp")


def _serialize_node(node: INode) -> Dict:
    if isinstance(node, INodeDirectory):
        d = {
            "k": "d", "n": node.name, "mt": node.mtime, "o": node.owner,
            "g": node.group, "pm": node.permission,
            "c": [_serialize_node(c) for c in node.children.values()],
        }
        if node.ec_policy:
            d["ec"] = node.ec_policy
        if node.ns_quota >= 0 or node.space_quota >= 0:
            d["nq"], d["sq"] = node.ns_quota, node.space_quota
        if node.snapshottable:
            d["snap"] = {name: _serialize_node(root)
                         for name, root in (node.snapshots or {}).items()}
        return _common_attrs(node, d)
    f: INodeFile = node  # type: ignore[assignment]
    d = {
        "k": "f", "n": f.name, "mt": f.mtime, "o": f.owner, "g": f.group,
        "pm": f.permission, "rep": f.replication, "bs": f.block_size,
        "uc": f.under_construction, "cl": f.client_name,
        "b": [b.to_wire() for b in f.blocks],
    }
    if f.ec_policy:
        d["ec"] = f.ec_policy
    return _common_attrs(f, d)


def _deserialize_node(d: Dict) -> INode:
    if d["k"] == "d":
        node = INodeDirectory(d["n"], owner=d.get("o", ""),
                              permission=d.get("pm", 0o755))
        _restore_common(node, d)
        node.ec_policy = d.get("ec")
        node.ns_quota = d.get("nq", -1)
        node.space_quota = d.get("sq", -1)
        if "snap" in d:
            node.snapshottable = True
            node.snapshots = {name: _deserialize_node(sd)
                              for name, sd in d["snap"].items()}
        for cd in d.get("c", []):
            node.add_child(_deserialize_node(cd))
        return node
    f = INodeFile(d["n"], d.get("rep", 3), d.get("bs", 0),
                  owner=d.get("o", ""), permission=d.get("pm", 0o644),
                  ec_policy=d.get("ec"))
    _restore_common(f, d)
    f.under_construction = d.get("uc", False)
    f.client_name = d.get("cl")
    f.blocks = [Block.from_wire(b) for b in d.get("b", [])]
    return f


class FSImage:
    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def save(self, fsdir: FSDirectory, txid: int, extra: Dict) -> str:
        """Checkpoint the namespace as of ``txid``. ``extra`` carries counters
        that must survive restart (next block id, generation stamp, leases)."""
        payload = pack({
            "v": 1, "txid": txid, "extra": extra,
            "root": _serialize_node(fsdir.root),
            "inodes": fsdir.num_inodes(),
        })
        digest = hashlib.md5(payload).hexdigest()
        final = os.path.join(self.dir, f"fsimage_{txid:019d}")
        tmp = final + ".ckpt"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        with open(final + ".md5", "w") as f:
            f.write(digest)
        return final

    def newest_image(self) -> Optional[Tuple[int, str]]:
        best: Optional[Tuple[int, str]] = None
        for name in os.listdir(self.dir):
            if name.startswith("fsimage_") and not name.endswith((".md5", ".ckpt")):
                txid = int(name.split("_", 1)[1])
                if best is None or txid > best[0]:
                    best = (txid, os.path.join(self.dir, name))
        return best

    def load(self) -> Optional[Tuple[int, FSDirectory, Dict]]:
        """Load the newest image; returns (txid, fsdir, extra) or None."""
        newest = self.newest_image()
        if newest is None:
            return None
        txid, path = newest
        with open(path, "rb") as f:
            payload = f.read()
        md5_path = path + ".md5"
        if os.path.exists(md5_path):
            with open(md5_path) as f:
                want = f.read().strip()
            got = hashlib.md5(payload).hexdigest()
            if want != got:
                raise IOError(f"fsimage {path} is corrupt "
                              f"(md5 {got} != recorded {want})")
        d = unpack(payload)
        fsdir = FSDirectory()
        fsdir.root = _deserialize_node(d["root"])  # type: ignore[assignment]
        fsdir._inode_count = d.get("inodes", 1)
        return d["txid"], fsdir, d.get("extra", {})

    def purge_old(self, keep: int = 2) -> None:
        """Retain the newest ``keep`` images. Ref: NNStorageRetentionManager."""
        images: List[Tuple[int, str]] = []
        for name in os.listdir(self.dir):
            if name.startswith("fsimage_") and not name.endswith((".md5", ".ckpt")):
                images.append((int(name.split("_", 1)[1]),
                               os.path.join(self.dir, name)))
        for _, path in sorted(images)[:-keep]:
            os.remove(path)
            if os.path.exists(path + ".md5"):
                os.remove(path + ".md5")
