"""FSNamesystem — the metadata master's brain.

Parity with the reference (ref: server/namenode/FSNamesystem.java (8,756 LoC;
:766 loadFromDisk, :2598 startFile), NameNodeRpcServer.java:781): composes the
inode tree (inodes.py), edit log (editlog.py), image (fsimage.py), leases
(lease.py), and block manager (blockmanager.py) behind one instrumented RW
lock, with the reference's locking discipline: mutate + log_edit under the
write lock, ``log_sync`` after releasing it (group commit), reads under the
read lock.

Startup = newest image + replay of later edits (ref: FSNamesystem
.loadFromDisk). Every mutation is durable before its RPC returns.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.namenode import editlog as el
from hadoop_tpu.dfs.namenode.blockmanager import BlockManager
from hadoop_tpu.dfs.namenode.editlog import FSEditLog, FileJournalManager
from hadoop_tpu.dfs.namenode.fsimage import FSImage
from hadoop_tpu.dfs.namenode.inodes import (FSDirectory, INodeDirectory,
                                            INodeFile, collect_blocks,
                                            iter_tree, snapshot_copy,
                                            subtree_counts)
from hadoop_tpu.dfs.namenode.lease import LeaseManager
from hadoop_tpu.dfs.namenode.permissions import (EXECUTE as PERM_X,
                                                 READ as PERM_R,
                                                 WRITE as PERM_W)
from hadoop_tpu.dfs.namenode.namesystem_lock import NamesystemLock
from hadoop_tpu.dfs.protocol.records import (AlreadyBeingCreatedError, Block,
                                             DatanodeInfo, FileStatus,
                                             LeaseExpiredError, LocatedBlock,
                                             NotReplicatedYetError,
                                             SafeModeError)
from hadoop_tpu.io import erasurecode as ec
from hadoop_tpu.dfs.protocol.records import QuotaExceededError
from hadoop_tpu.metrics import metrics_system
from hadoop_tpu.security.ugi import current_user

log = logging.getLogger(__name__)

# The audit trail (ref: FSNamesystem.java:392 logAuditEvent + the
# "FSNamesystem.audit" logger convention): one line per namespace op with
# the caller's identity and address from the RPC CallContext. Operators
# attach handlers/sinks to THIS logger name — it rotates/routes with
# whatever logging config the deployment already runs, and the
# dynamometer replays it (tools/dynamometer.parse_audit_line tolerates
# the extra fields). ``status`` distinguishes ok from failed(Type) —
# failure lines come from the RPC-facade auditor
# (dfs/namenode/audit.py), success lines from these call sites — and
# ``trace_id`` joins each line to the telemetry plane: grep the audit
# log, assemble the trace at the fleet doctor.
audit_log = logging.getLogger("hadoop_tpu.audit")

AUDIT_ENABLE_KEY = "namenode.audit.enable"

# conf-keyed master switch (namenode.audit.enable, default on —
# the seed always logged); FSNamesystem.__init__ resolves it
_audit_enabled = True


def set_audit_enabled(enabled: bool) -> None:
    global _audit_enabled
    _audit_enabled = bool(enabled)


def log_audit_event(allowed: bool, cmd: str, src: str,
                    dst: Optional[str] = None,
                    status: str = "ok") -> None:
    """Ref: FSNamesystem.logAuditEvent — ugi/ip/cmd/src/dst(+CallerContext
    = the RPC client id, its role here)."""
    if not _audit_enabled or not audit_log.isEnabledFor(logging.INFO):
        return
    from hadoop_tpu.ipc.server import current_call
    from hadoop_tpu.tracing.tracer import current_span
    call = current_call()
    ugi = call.user.user_name if call else current_user().user_name
    ip = call.address if call else "local"
    ctx = call.client_id.hex()[:16] if call and call.client_id else "-"
    sp = current_span()
    trace = f"{sp.trace_id:016x}" if sp is not None and sp.sampled \
        else "-"
    audit_log.info(
        "allowed=%s\tugi=%s\tip=%s\tcmd=%s\tsrc=%s\tdst=%s"
        "\tcallerContext=%s\tstatus=%s\ttrace_id=%s",
        str(allowed).lower(), ugi, ip, cmd, src, dst or "null", ctx,
        status, trace)


# Ref: BlockStoragePolicySuite — policy ids the mover acts on. On a
# homogeneous TPU-host fleet these are placement intents, not media types.
STORAGE_POLICIES = ("HOT", "WARM", "COLD", "ALL_SSD", "ONE_SSD",
                    "LAZY_PERSIST", "PROVIDED")


class FSNamesystem:
    def __init__(self, conf: Configuration, name_dir: str,
                 journal_manager=None):
        self.conf = conf
        self.name_dir = name_dir
        self.default_block_size = conf.get_size_bytes("dfs.blocksize",
                                                      128 * 1024 * 1024)
        self.default_replication = conf.get_int("dfs.replication", 3)
        self.lock = NamesystemLock(
            write_warn_threshold_s=conf.get_time_seconds(
                "dfs.namenode.write-lock-reporting-threshold", 1.0))
        self.fsdir = FSDirectory()
        # Permission enforcement (ref: FSNamesystem.isPermissionEnabled
        # + FSPermissionChecker): stored owner/group/mode bits are
        # CHECKED on every namespace op, not just recorded. The
        # NameNode's own user is the superuser; members of the
        # configured supergroup bypass too.
        self._perm_enabled = conf.get_bool("dfs.permissions.enabled",
                                           True)
        self._superuser = current_user().user_name
        # audit-plane master switch (process-global like the logger
        # itself; the last namesystem to init in a shared-process
        # minicluster wins, which is fine — one conf lineage)
        set_audit_enabled(conf.get_bool(AUDIT_ENABLE_KEY, True))
        self._supergroup = conf.get("dfs.permissions.superusergroup",
                                    "supergroup")
        # Server-side group resolution — NEVER the client-asserted UGI
        # groups, which would let any caller claim the supergroup
        # (ref: security/Groups.java).
        from hadoop_tpu.security.groups import Groups
        self._groups = Groups(conf)
        self.image = FSImage(os.path.join(name_dir, "image"))
        # Journal seam: local directory by default, quorum journal in HA
        # (ref: FSEditLog's JournalSet of FileJournalManager/QJM members).
        self.editlog = FSEditLog(journal_manager or FileJournalManager(
            os.path.join(name_dir, "edits")))
        self.leases = LeaseManager(
            soft_limit_s=conf.get_time_seconds("dfs.lease.soft-limit", 60.0),
            hard_limit_s=conf.get_time_seconds("dfs.lease.hard-limit", 1200.0))
        self.bm = BlockManager(conf)
        # Data-transfer encryption keys (ref: BlockTokenSecretManager's
        # DataEncryptionKey minting under dfs.encrypt.data.transfer):
        # clients fetch the current key, DNs fetch the full set.
        self.data_encryption_keys = None
        if conf.get_bool("dfs.encrypt.data.transfer", False):
            # Fail fast on the incompatible combination: on a secured
            # cluster DEKs are only served over privacy-QoP RPC, so
            # anything below privacy would strand every DN/client at
            # key-fetch time with nothing but a DEBUG log to show for
            # it. Surface the misconfiguration at NN startup instead.
            auth = conf.get("hadoop.security.authentication",
                            "simple").lower()
            qop = conf.get("hadoop.rpc.protection",
                           "authentication").lower()
            if auth == "sasl" and qop != "privacy":
                raise ValueError(
                    "dfs.encrypt.data.transfer=true on a secured cluster "
                    "requires hadoop.rpc.protection=privacy (got "
                    f"{qop!r}): data encryption keys are only served "
                    "over privacy-protected RPC")
            from hadoop_tpu.dfs.protocol.datatransfer import \
                DataEncryptionKeys
            self.data_encryption_keys = DataEncryptionKeys()
        # Block access tokens (ref: dfs.block.access.token.enable +
        # BlockTokenSecretManager.java:66): minted into every
        # LocatedBlock, verified by DNs on every data-plane op that
        # names a block — including fd-passing short-circuit grants.
        self.block_tokens = None
        if conf.get_bool("dfs.block.access.token.enable", False):
            from hadoop_tpu.dfs.protocol.blocktoken import \
                BlockTokenSecretManager
            self.block_tokens = BlockTokenSecretManager()
        # PROVIDED storage alias map (ref: hdfs server/aliasmap/
        # InMemoryAliasMap.java + common/blockaliasmap/ — block id →
        # location in an external store; DNs resolve provided reads
        # through it). Persisted with the image; populated by fs2img.
        self.alias_map: Dict[int, Dict] = {}
        self._next_block_id = 1 << 30   # ref: SequentialBlockIdGenerator  # guarded-by: _id_lock
        self._next_group_id = ec.STRIPED_ID_BASE  # striped block groups
        self._gen_stamp = 1000          # ref: GenerationStamp  # guarded-by: _id_lock
        self._id_lock = threading.Lock()
        # paths mid block-recovery, pinned to their INode identity: the
        # sweep must never act on a path that now names a DIFFERENT file
        # (delete + recreate while recovery was in flight)
        self._pending_recovery: Dict[str, INodeFile] = {}  # guarded-by: lock
        # Centralized cache directives (ref: namenode/CacheManager.java):
        # id → path; the cache monitor reconciles DN state against them.
        self.cache_directives: Dict[int, str] = {}
        self._next_cache_id = 1
        from hadoop_tpu.dfs.namenode.sps import StoragePolicySatisfier
        self.sps = StoragePolicySatisfier(self)
        self._snapshot_count = 0             # namespace-wide, for fast paths
        reg = metrics_system().source("namenode.ops")
        self._m = {name: reg.rate(name) for name in
                   ("create", "add_block", "complete", "get_block_locations",
                    "mkdirs", "delete", "rename", "listing", "get_file_info")}
        self._m_files = reg.register_callback_gauge(
            "files_total", self.fsdir.num_inodes)

    # ----------------------------------------------------------- permissions

    @staticmethod
    def check_path_names(*paths: str) -> None:
        """Reject "." / ".." as COMPONENT names on name-CREATING ops
        (ref: DFSUtil.isValidName, validated at the write boundary):
        the namespace walks literally, so a directory literally named
        ".." would make POSIX-normalizing clients and every
        prefix-based rule (trash containment, encryption zones, mount
        tables) address a different node than the one stored. Replay
        and read/delete paths stay permissive so a legacy tree can
        still be cleaned up."""
        for p in paths:
            for c in p.split("/"):
                if c in (".", ".."):
                    raise ValueError(
                        f"invalid path component {c!r} in {p!r}")


    def check_access(self, path: str, *, parent: int = 0,
                     target: int = 0, owner_only: bool = False,
                     sub_dirs: int = 0) -> None:
        """Enforce the stored mode bits for the CURRENT RPC caller
        (ref: FSNamesystem's per-op FSPermissionChecker use). No-op when
        dfs.permissions.enabled=false or the caller is the superuser.

        Group resolution rides Groups' per-user TTL cache; a cache miss
        does the OS lookup while the namesystem lock is held (callers
        invoke this inside their lock block for check/op atomicity) —
        once per user per 5 minutes, the documented trade for not
        threading a pre-lock resolution step through every op."""
        if not self._perm_enabled:
            return
        from hadoop_tpu.dfs.namenode.permissions import FSPermissionChecker
        from hadoop_tpu.ipc.server import current_call
        call = current_call()
        ugi = call.user if call else current_user()
        FSPermissionChecker(
            ugi.user_name, self._groups.groups_for(ugi.user_name),
            self._superuser,
            self._supergroup).check(self.fsdir, path, parent=parent,
                                    target=target, owner_only=owner_only,
                                    sub_dirs=sub_dirs)

    def check_superuser(self, what: str) -> None:
        """Ref: FSPermissionChecker.checkSuperuserPrivilege — admin-class
        ops (quota, chown, snapshot admin, encryption zones)."""
        if not self._perm_enabled:
            return
        from hadoop_tpu.dfs.namenode.permissions import FSPermissionChecker
        from hadoop_tpu.ipc.server import current_call
        from hadoop_tpu.security.ugi import AccessControlError
        call = current_call()
        ugi = call.user if call else current_user()
        pc = FSPermissionChecker(
            ugi.user_name, self._groups.groups_for(ugi.user_name),
            self._superuser, self._supergroup)
        if not pc.is_superuser:
            raise AccessControlError(
                f"Superuser privilege required for {what} "
                f"(user={ugi.user_name})")

    def _check_set_owner_access(self, path: str, inode, owner: str,
                                group: str) -> None:
        """Ref: FSDirAttrOp.setOwner — changing the OWNER is superuser
        territory, but a file's owner may chgrp it to any group they
        belong to (server-resolved, never client-asserted)."""
        if not self._perm_enabled:
            return
        from hadoop_tpu.dfs.namenode.permissions import FSPermissionChecker
        from hadoop_tpu.ipc.server import current_call
        from hadoop_tpu.security.ugi import AccessControlError
        call = current_call()
        ugi = call.user if call else current_user()
        pc = FSPermissionChecker(
            ugi.user_name, self._groups.groups_for(ugi.user_name),
            self._superuser, self._supergroup)
        if pc.is_superuser:
            return
        if owner and owner != inode.owner:
            raise AccessControlError(
                f"Superuser privilege required to change the owner of "
                f"\"{path}\" (user={ugi.user_name})")
        if ugi.user_name != inode.owner:
            raise AccessControlError(
                f"Permission denied: user={ugi.user_name} is not the "
                f"owner of inode \"{path}\" (owner={inode.owner})")
        if group and group not in pc.groups:
            raise AccessControlError(
                f"Permission denied: user={ugi.user_name} does not "
                f"belong to group {group!r}")

    # ------------------------------------------------------------- lifecycle

    def load_from_disk(self, open_edits: bool = True) -> int:
        """Ref: FSNamesystem.loadFromDisk:766 — image then edits replay.
        ``open_edits=False`` loads read-only (HA standby: the tailer keeps
        applying and a later transition opens the journal for write).
        Returns the last applied txid."""
        last_txid = 0
        loaded = self.image.load()
        if loaded is not None:
            last_txid, self.fsdir, extra = loaded
            with self._id_lock:
                self._next_block_id = extra.get(
                    "next_block_id", self._next_block_id)
                self._next_group_id = extra.get(
                    "next_group_id", self._next_group_id)
                self._gen_stamp = extra.get("gen_stamp", self._gen_stamp)
            self.leases.restore_from_image(extra.get("leases", {}))
            self.alias_map = {int(k): v for k, v in
                              extra.get("alias_map", {}).items()}
            self.cache_directives = {
                int(k): v for k, v in
                extra.get("cache_directives", {}).items()}
            self._next_cache_id = extra.get("next_cache_id", 1)
        # Count image-loaded snapshots BEFORE replay: replayed
        # delete-snapshot ops consult the counter for pin checks.
        self._snapshot_count = sum(
            len(n.snapshots or {}) for n in iter_tree(self.fsdir.root)
            if isinstance(n, INodeDirectory))
        replayed = 0
        for rec in self.editlog.journal.read_edits(last_txid + 1):
            self._apply_edit(rec)
            last_txid = rec["t"]
            replayed += 1
        log.info("Loaded namespace: %d inodes, replayed %d edits, txid=%d",
                 self.fsdir.num_inodes(), replayed, last_txid)
        self._rebuild_block_map()
        if open_edits:
            self.editlog.open_for_write(last_txid)
        self.bm.safemode.set_block_total(self.bm.num_blocks())
        return last_txid

    def _rebuild_block_map(self) -> None:
        """Blocks live in inodes after load; register them with the BM
        (locations arrive via block reports, as in the reference). Also
        recover the id/stamp generators past everything ever allocated —
        reusing a block id after restart would collide with live replicas
        (ref: SequentialBlockIdGenerator skipTo on image load)."""
        for node in iter_tree(self.fsdir.root):
            if isinstance(node, INodeFile):
                for b in node.blocks:
                    if b.block_id in self.alias_map:
                        # PROVIDED blocks have no DN replicas: keeping
                        # them out of the BM keeps them out of safemode
                        # accounting and the redundancy queues (ref:
                        # ProvidedStorageMap bypassing block reports).
                        self._track_block_id(b.to_wire())
                        continue
                    info = self._register_block_locked(node, b)
                    info.under_construction = node.under_construction and \
                        b is node.blocks[-1]
                    self._track_block_id(b.to_wire())
            elif isinstance(node, INodeDirectory) and node.snapshots:
                # Snapshot-pinned blocks whose live file is gone must stay
                # known, or block reports would invalidate their replicas.
                for snap in node.snapshots.values():
                    for f in iter_tree(snap):
                        if isinstance(f, INodeFile):
                            for b in f.blocks:
                                info = self._register_block_locked(f, b)
                                info.under_construction = False
                                self._track_block_id(b.to_wire())

    def _register_block_locked(self, inode: INodeFile, b: Block):
        """Idempotently register an inode's block with the block manager
        (replay/tailing path — locations already reported must survive)."""
        info = self.bm.get(b.block_id)
        if info is not None:
            info.block.num_bytes = max(info.block.num_bytes, b.num_bytes)
            if b.gen_stamp > info.block.gen_stamp:
                info.block.gen_stamp = b.gen_stamp
            return info
        if inode.ec_policy:
            return self.bm.add_striped_block_collection(
                b, inode, ec.get_policy(inode.ec_policy))
        return self.bm.add_block_collection(b, inode, inode.replication)

    def save_namespace(self) -> str:
        """Checkpoint. Ref: FSNamesystem.saveNamespace — requires safemode in
        the reference; here we hold the write lock for the (in-memory)
        serialize, then roll the edit log."""
        with self.lock.write():
            txid = self.editlog.last_txid
            path = self.image.save(self.fsdir, txid, self.image_extra())
        self.editlog.roll()
        self.image.purge_old()
        return path

    def image_extra(self) -> Dict:
        """Counters that must survive restart alongside the image — the
        single source for both the local checkpointer and the standby's
        (drift here would lose id/stamp state across failover)."""
        with self._id_lock:
            ids = {"next_block_id": self._next_block_id,
                   "next_group_id": self._next_group_id,
                   "gen_stamp": self._gen_stamp}
        return {
            **ids,
            "leases": self.leases.snapshot_for_image(),
            "cache_directives": dict(self.cache_directives),
            "next_cache_id": self._next_cache_id,
            "alias_map": {str(k): v for k, v in self.alias_map.items()},
        }

    def close(self) -> None:
        try:
            self.editlog.close()
        except Exception:
            log.exception("Error closing edit log")

    # ----------------------------------------------------------- id helpers

    def _new_block_id(self) -> int:
        with self._id_lock:
            self._next_block_id += 1
            return self._next_block_id

    def _new_group_id(self) -> int:
        with self._id_lock:
            self._next_group_id += ec.MAX_UNITS
            return self._next_group_id

    def _track_block_id(self, bw: Dict) -> None:
        """Advance the id/stamp high-water marks past a (re)played block."""
        bid, gs = bw.get("id", 0), bw.get("gs", 0)
        with self._id_lock:
            if ec.is_striped_id(bid):
                if ec.group_id_of(bid) > self._next_group_id:
                    self._next_group_id = ec.group_id_of(bid)
            elif bid > self._next_block_id:
                self._next_block_id = bid
            if gs > self._gen_stamp:
                self._gen_stamp = gs

    def current_gen_stamp(self) -> int:
        with self._id_lock:
            return self._gen_stamp

    def next_gen_stamp(self) -> int:
        with self._id_lock:
            self._gen_stamp += 1
            gs = self._gen_stamp
        # Persisted so restarts never reuse stamps (ref: OP_SET_GENSTAMP_V2).
        txid = self.editlog.log_edit(el.OP_SET_GENSTAMP, {"gs": gs})
        self.editlog.log_sync(txid)
        return gs

    def _check_not_safemode(self, action: str) -> None:
        if self.bm.safemode.is_on():
            raise SafeModeError(
                f"cannot {action}: name node is in safe mode "
                f"({self.bm.safemode.status()})")

    @staticmethod
    def _check_mutable_path(*paths: str) -> None:
        """Snapshot contents are immutable and the .snapshot pseudo-dir is
        not a real inode — every mutating op must reject such paths (ref:
        FSDirectory.verifySnapshotName / the isSnapshotPath checks)."""
        for p in paths:
            if ".snapshot" in [c for c in p.split("/") if c]:
                raise OSError(
                    f"cannot modify {p}: snapshot paths are read-only")

    # ========================================================== client ops

    def create(self, path: str, client_name: str, replication: Optional[int],
               block_size: Optional[int], overwrite: bool) -> FileStatus:
        """Ref: FSNamesystem.startFile:2598."""
        replication = replication or self.default_replication
        block_size = block_size or self.default_block_size
        owner = current_user().user_name
        # EDEK pre-generation OUTSIDE the namesystem lock (the KMS is an
        # HTTP round trip; ref: the reference's EDEKCacheLoader exists for
        # exactly this reason). Re-checked under the lock.
        pre_zone_key = None
        pre_edek = None
        if self._kms() is not None:
            with self.lock.read():
                pre_zone_key = self._zone_key_locked(path)
            if pre_zone_key is not None:
                pre_edek = self._generate_edek_attr(pre_zone_key)
        self.check_path_names(path)
        with self._m["create"].time():
            with self.lock.write():
                self._check_not_safemode("create")
                self._check_mutable_path(path)
                # under the lock so the check is atomic with the op
                # (ref: the reference checks via FSPermissionChecker
                # inside the namesystem lock): ancestors traversable +
                # parent writable; an existing target (overwrite) must
                # itself be writable
                self.check_access(path, parent=PERM_W, target=PERM_W)
                existing = self.fsdir.get_inode(path)
                if existing is not None:
                    if isinstance(existing, INodeDirectory):
                        raise IsADirectoryError(path)
                    holder = self.leases.holder_of(path)
                    if holder is not None and holder != client_name:
                        if not self.leases.is_soft_expired(path):
                            raise AlreadyBeingCreatedError(
                                f"{path} is being written by {holder}")
                        self._recover_lease_locked(path, existing)
                    if not overwrite:
                        raise FileExistsError(path)
                    # Quota BEFORE the overwrite-delete (a rejection must
                    # leave the old file untouched) — but the replace is
                    # inode-neutral: the old file still counts, the new one
                    # takes its slot (ref: overwrite at quota is legal).
                    self._check_quota_locked(path, d_inodes=0, d_space=0)
                    self._delete_locked(path, recursive=False)
                else:
                    self._check_quota_locked(path, d_inodes=1, d_space=0)
                ec_policy = self._effective_ec_policy_locked(path)
                zone_key = self._zone_key_locked(path) \
                    if self._kms() is not None else None
                edek_attr = pre_edek if zone_key == pre_zone_key else None
                if zone_key is not None and edek_attr is None:
                    # zone appeared/changed between the optimistic read
                    # and now (rare) — pay the KMS call under the lock
                    edek_attr = self._generate_edek_attr(zone_key)
                inode = self.fsdir.add_file(path, replication, block_size,
                                            owner=owner)
                inode.ec_policy = ec_policy
                inode.under_construction = True
                inode.client_name = client_name
                self.leases.add_lease(client_name, path)
                txid = self.editlog.log_edit(el.OP_ADD, {
                    "p": path, "rep": replication, "bs": block_size,
                    "cl": client_name, "o": owner, "ov": overwrite,
                    "ec": ec_policy})
                if edek_attr is not None:
                    # atomic with create: same write lock, extra edit
                    # before the sync (ref: startFile's FEInfo handling)
                    inode.xattrs = {self.EDEK_XATTR: edek_attr}
                    txid = self.editlog.log_edit(el.OP_SET_XATTR, {
                        "p": path, "n": self.EDEK_XATTR, "v": edek_attr})
                status = inode.status(path)
            self.editlog.log_sync(txid)
            log_audit_event(True, "create", path)
            return status

    def add_block(self, path: str, client_name: str,
                  previous: Optional[Dict], exclude: List[str],
                  writer_host: Optional[str] = None) -> LocatedBlock:
        """Allocate the next block + choose its pipeline.
        Ref: FSNamesystem.getAdditionalBlock / NameNodeRpcServer.addBlock."""
        with self._m["add_block"].time():
            prev_block = Block.from_wire(previous) if previous else None
            with self.lock.write():
                self._check_not_safemode("add block")
                inode = self._check_lease_locked(path, client_name)
                if prev_block is not None:
                    self._commit_block_locked(inode, prev_block)
                last = inode.last_block()
                if last is not None:
                    info = self.bm.get(last.block_id)
                    min_rep = ec.get_policy(inode.ec_policy).k \
                        if inode.ec_policy else self.bm.min_replication
                    if info is not None and info.under_construction and \
                            info.live_replicas() < min_rep:
                        raise NotReplicatedYetError(
                            f"last block of {path} not yet minimally "
                            f"replicated ({info.live_replicas()})")
                self._check_quota_locked(
                    path, d_inodes=0,
                    d_space=inode.block_size * (
                        1 if inode.ec_policy else max(1, inode.replication)))
                offset = sum(b.num_bytes for b in inode.blocks)
                if inode.ec_policy:
                    policy = ec.get_policy(inode.ec_policy)
                    block = Block(self._new_group_id(),
                                  self.current_gen_stamp(), 0)
                    targets = self.bm.dn_manager.choose_targets(
                        policy.num_units, set(exclude), None)
                    if len(targets) < policy.k:
                        raise IOError(
                            f"not enough datanodes for {inode.ec_policy} "
                            f"({len(targets)} live, need >={policy.k})")
                    sinfo = self.bm.add_striped_block_collection(
                        block, inode, policy)
                    sinfo.rbw_locations = {t.uuid for t in targets}
                    for i, t in enumerate(targets):
                        sinfo.unit_map[t.uuid] = i
                    lb = LocatedBlock(
                        block, [t.public_info() for t in targets], offset,
                        ec_policy=policy.name,
                        indices=list(range(len(targets))))
                else:
                    from hadoop_tpu.dfs.protocol.records import (
                        POLICY_TYPES, effective_storage_policy)
                    block = Block(self._new_block_id(),
                                  self.current_gen_stamp(), 0)
                    targets = self.bm.dn_manager.choose_targets(
                        inode.replication, set(exclude), writer_host,
                        preferred_types=POLICY_TYPES.get(
                            effective_storage_policy(inode)))
                    if not targets:
                        raise IOError(
                            f"no datanodes available for {path} "
                            f"(live={len(self.bm.dn_manager.live_nodes())})")
                    info = self.bm.add_block_collection(block, inode,
                                                        inode.replication)
                    info.rbw_locations = {t.uuid for t in targets}
                    lb = LocatedBlock(
                        block, [t.public_info() for t in targets], offset)
                inode.blocks.append(block)
                txid = self.editlog.log_edit(el.OP_ADD_BLOCK, {
                    "p": path, "b": block.to_wire()})
            if self.block_tokens is not None:
                # the writer needs WRITE (pipeline) + READ (verify/reopen)
                from hadoop_tpu.dfs.protocol import blocktoken as bt
                lb.token = self.block_tokens.generate_token(
                    client_name, block.block_id,
                    (bt.MODE_READ, bt.MODE_WRITE))
            self.editlog.log_sync(txid)
            return lb

    def abandon_block(self, path: str, client_name: str, block: Dict) -> None:
        """Client gave up on a block (pipeline could not be built).
        Ref: FSNamesystem.abandonBlock."""
        blk = Block.from_wire(block)
        with self.lock.write():
            inode = self._check_lease_locked(path, client_name)
            inode.blocks = [b for b in inode.blocks
                            if b.block_id != blk.block_id]
            self.bm.remove_block(blk)
            txid = self.editlog.log_edit(el.OP_UPDATE_BLOCKS, {
                "p": path, "b": [b.to_wire() for b in inode.blocks]})
        self.editlog.log_sync(txid)

    def complete(self, path: str, client_name: str,
                 last: Optional[Dict]) -> bool:
        """Finalize the file. Ref: FSNamesystem.completeFile."""
        with self._m["complete"].time():
            with self.lock.write():
                inode = self._check_lease_locked(path, client_name)
                if last is not None:
                    self._commit_block_locked(inode, Block.from_wire(last))
                lb = inode.last_block()
                if lb is not None:
                    info = self.bm.get(lb.block_id)
                    min_rep = ec.get_policy(inode.ec_policy).k \
                        if inode.ec_policy else self.bm.min_replication
                    if info is not None and \
                            info.live_replicas() < min_rep:
                        return False  # client retries (ref: completeFile loop)
                inode.under_construction = False
                inode.client_name = None
                inode.mtime = time.time()
                self.leases.remove_lease(client_name, path)
                txid = self.editlog.log_edit(el.OP_CLOSE, {
                    "p": path, "b": [b.to_wire() for b in inode.blocks]})
            self.editlog.log_sync(txid)
            return True

    def _commit_block_locked(self, inode: INodeFile, reported: Block) -> None:
        """Record the client-reported final length/genstamp of a block."""
        for b in inode.blocks:
            if b.block_id == reported.block_id:
                b.num_bytes = reported.num_bytes
                b.gen_stamp = max(b.gen_stamp, reported.gen_stamp)
                self.bm.complete_block(b)
                return

    def _check_lease_locked(self, path: str, client_name: str) -> INodeFile:
        inode = self.fsdir.get_inode(path)
        if inode is None or not isinstance(inode, INodeFile):
            raise FileNotFoundError(f"no such file {path}")
        holder = self.leases.holder_of(path)
        if holder != client_name:
            raise LeaseExpiredError(
                f"lease on {path} held by {holder!r}, not {client_name!r}")
        return inode

    def update_pipeline(self, client_name: str, path: str, old_block: Dict,
                        new_gs: int, new_len: int) -> None:
        """Pipeline recovery bumped the gen stamp.
        Ref: FSNamesystem.updatePipeline."""
        blk = Block.from_wire(old_block)
        with self.lock.write():
            inode = self._check_lease_locked(path, client_name)
            for b in inode.blocks:
                if b.block_id == blk.block_id:
                    b.gen_stamp = new_gs
                    b.num_bytes = new_len
                    info = self.bm.get(b.block_id)
                    if info is not None:
                        info.block.gen_stamp = new_gs
                        # Replicas from the failed pipeline are now stale.
                        info.locations.clear()
                    break
            txid = self.editlog.log_edit(el.OP_UPDATE_BLOCKS, {
                "p": path, "b": [b.to_wire() for b in inode.blocks]})
        self.editlog.log_sync(txid)

    def renew_lease(self, client_name: str) -> None:
        self.leases.renew_lease(client_name)

    def recover_lease(self, path: str, new_holder: str) -> bool:
        """Explicit lease recovery (ref: FSNamesystem.recoverLease). Returns
        True when the file is closed and available."""
        with self.lock.write():
            self.check_access(path, target=PERM_W)
            inode = self.fsdir.get_inode(path)
            if inode is None or not isinstance(inode, INodeFile):
                raise FileNotFoundError(path)
            if not inode.under_construction:
                return True
            if not self.leases.is_soft_expired(path):
                raise AlreadyBeingCreatedError(
                    f"{path} lease not yet soft-expired")
            self._recover_lease_locked(path, inode)
            return not inode.under_construction

    def _recover_lease_locked(self, path: str, inode: INodeFile) -> bool:  # lint: holds=lock
        """Release an abandoned under-construction file. Two phases, like the
        reference (ref: FSNamesystem.internalReleaseLease →
        BlockUnderConstructionFeature.initializeBlockRecovery):

        1. The trailing UC block has no finalized replica but known pipeline
           members → issue RECOVER commands (gen-stamp bump; each DN
           finalizes its rbw replica at its length and reports it) and leave
           the file open-pending; a later pass closes it.
        2. Finalized replicas exist (or recovery completed) → commit lengths
           and close. A trailing block nothing durable is known about is
           dropped.

        Returns True when the file is closed.
        """
        holder = self.leases.holder_of(path)
        if holder:
            self.leases.remove_lease(holder, path)
        last = inode.last_block()
        if last is not None:
            info = self.bm.get(last.block_id)
            if info is not None and info.under_construction and \
                    info.live_replicas() == 0:
                if info.rbw_locations and \
                        self._start_block_recovery_locked(path, info):
                    return False  # recovery in flight; close on a later pass
                # Nothing recoverable: drop the trailing block.
                inode.blocks.pop()
                self.bm.remove_block(last)
        self._pending_recovery.pop(path, None)
        inode.under_construction = False
        inode.client_name = None
        from hadoop_tpu.dfs.namenode.blockmanager import BlockInfoStriped
        for b in inode.blocks:
            info = self.bm.get(b.block_id)
            if isinstance(info, BlockInfoStriped) and b.num_bytes == 0:
                # Group length was never committed by the client; derive it
                # from the finalized unit lengths the DNs reported.
                b.num_bytes = info.logical_length()
                info.block.num_bytes = b.num_bytes
            elif info is not None and info.block.num_bytes > b.num_bytes:
                b.num_bytes = info.block.num_bytes  # recovered length
            self.bm.complete_block(b)
        txid = self.editlog.log_edit(el.OP_CLOSE, {
            "p": path, "b": [b.to_wire() for b in inode.blocks]})
        self.editlog.log_sync(txid)
        log.info("Recovered lease on %s (was held by %s)", path, holder)
        return True

    def _start_block_recovery_locked(self, path: str,  # lint: holds=lock
                                     info) -> bool:
        """Queue RECOVER commands to the expected pipeline members.
        Returns False when no member is live (recovery impossible)."""
        nodes = [self.bm.dn_manager.get(u) for u in info.rbw_locations]
        nodes = [n for n in nodes if n is not None
                 and n.state != "dead"]
        if not nodes:
            return False
        if self._pending_recovery.get(path) is info.inode:
            return True  # already issued; waiting for reports
        new_gs = self.next_gen_stamp()
        old_block = Block(info.block.block_id, info.block.gen_stamp,
                          info.block.num_bytes)
        info.block.gen_stamp = new_gs
        for b in info.inode.blocks:
            if b.block_id == info.block.block_id:
                b.gen_stamp = new_gs
        from hadoop_tpu.dfs.namenode.blockmanager import BlockInfoStriped
        for node in nodes:
            if isinstance(info, BlockInfoStriped):
                # DNs store unit replicas, not the group: recover the unit
                # this node was assigned at allocation time.
                idx = info.unit_map.get(node.uuid)
                if idx is None:
                    continue
                unit = Block(old_block.block_id + idx, old_block.gen_stamp)
                node.recover_queue.append((unit, new_gs))
            else:
                node.recover_queue.append((old_block, new_gs))
        self._pending_recovery[path] = info.inode
        log.info("Started block recovery of %s for %s on %d nodes "
                 "(gs %d -> %d)", info.block, path, len(nodes),
                 old_block.gen_stamp, new_gs)
        return True

    def check_pending_recoveries(self) -> None:
        """Second phase of lease recovery: close files whose block recovery
        reported back. Ref: commitBlockSynchronization's role."""
        with self.lock.read():
            pending = list(self._pending_recovery.items())
        for path, expected in pending:
            with self.lock.write():
                inode = self.fsdir.get_inode(path)
                if inode is not expected:
                    # path deleted, or recreated as a DIFFERENT file a
                    # client is actively writing — either way this
                    # recovery no longer applies (force-closing the new
                    # file would drop a live writer's data)
                    self._pending_recovery.pop(path, None)
                    continue
                if not inode.under_construction:
                    self._pending_recovery.pop(path, None)
                    continue
                last = inode.last_block()
                info = self.bm.get(last.block_id) if last else None
                if info is not None and info.live_replicas() > 0:
                    self._recover_lease_locked(path, inode)

    def check_leases(self) -> None:
        """Periodic hard-limit sweep. Ref: LeaseManager.Monitor."""
        for path in self.leases.hard_expired_paths():
            with self.lock.write():
                inode = self.fsdir.get_inode(path)
                # re-verify expiry UNDER the lock: between the snapshot
                # and here the writer may have renewed, or the path may
                # now be a different, actively-written file (delete +
                # recreate) holding a fresh lease
                if isinstance(inode, INodeFile) and \
                        inode.under_construction and \
                        self.leases.is_hard_expired(path):
                    self._recover_lease_locked(path, inode)
        self.check_pending_recoveries()

    # ------------------------------------------------------------ reads

    def add_provided_file(self, path: str, external_uri: str,
                          length: int,
                          block_size: Optional[int] = None) -> Dict:
        """Mount one external file as a PROVIDED-storage DFS file: the
        namespace entry + alias-map blocks, no data copied (ref: the
        fs2img ImageWriter's per-file treatment — here applied to the
        live namesystem, checkpointed with the image).
        """
        # admin surface: injects externally-backed blocks into the
        # namespace (the fs2img tool's op) — superuser only, like the
        # reference's image-import path
        self.check_superuser("addProvidedFile")
        self.check_path_names(path)
        block_size = block_size or self.default_block_size
        owner = current_user().user_name
        with self.lock.write():
            self._check_not_safemode("add provided file")
            self._check_mutable_path(path)
            if self.fsdir.exists(path):
                raise FileExistsError(path)
            inode = self.fsdir.add_file(path, 1, block_size, owner=owner)
            blocks = []
            off = 0
            while off < length or not blocks:
                n = min(block_size, length - off)
                blk = Block(self._new_block_id(),
                            self.current_gen_stamp(), n)
                self.alias_map[blk.block_id] = {
                    "uri": external_uri, "offset": off, "length": n}
                inode.blocks.append(blk)
                blocks.append(blk)
                off += n
                if length == 0:
                    break
            inode.under_construction = False
            txid = self.editlog.log_edit(el.OP_PROVIDED_FILE, {
                "p": path, "uri": external_uri, "len": length,
                "bs": block_size, "o": owner,
                "b": [b.to_wire() for b in blocks]})
        self.editlog.log_sync(txid)
        log_audit_event(True, "addProvidedFile", path)
        return inode.status(path).to_wire()

    def get_block_alias(self, block_id: int) -> Optional[Dict]:
        with self.lock.read():
            alias = self.alias_map.get(block_id)
            return dict(alias) if alias else None

    def get_block_locations(self, path: str, offset: int,
                            length: int) -> Dict:
        """Ref: FSNamesystem.getBlockLocations (+ the sortLocatedBlocks
        call that orders replicas closest-to-reader-first)."""
        from hadoop_tpu.ipc.server import current_call
        call = current_call()
        reader_host = call.address.rsplit(":", 1)[0] if call else None
        log_audit_event(True, "open", path)
        with self._m["get_block_locations"].time():
            with self.lock.read():
                self.check_access(path, target=PERM_R)
                inode = self.fsdir.get_inode(path)
                if inode is None or not isinstance(inode, INodeFile):
                    raise FileNotFoundError(path)
                blocks: List[LocatedBlock] = []
                pos = 0
                for b in inode.blocks:
                    if pos + b.num_bytes > offset and pos < offset + length:
                        if b.block_id in self.alias_map:
                            # PROVIDED block: any DN can serve it by
                            # fetching from the external store (ref:
                            # ProvidedStorageMap fabricating locations
                            # for the provided storage id).
                            locs = [n.public_info() for n in
                                    self.bm.dn_manager.live_nodes()[:3]]
                            blocks.append(LocatedBlock(b, locs, pos))
                        else:
                            blocks.append(self.bm.located_block(
                                b, pos, reader_host=reader_host))
                    pos += b.num_bytes
                if self.block_tokens is not None:
                    from hadoop_tpu.dfs.protocol import blocktoken as bt
                    user = current_user().user_name
                    for lb in blocks:
                        lb.token = self.block_tokens.generate_token(
                            user, lb.block.block_id, (bt.MODE_READ,))
                return {
                    "length": inode.length(),
                    "blocks": [lb.to_wire() for lb in blocks],
                    "uc": inode.under_construction,
                }

    def get_file_info(self, path: str) -> Optional[Dict]:
        with self._m["get_file_info"].time():
            with self.lock.read():
                # traverse only — stat needs x on the ancestors
                self.check_access(path)
                inode = self.fsdir.get_inode(path)
                return None if inode is None else inode.status(path).to_wire()

    def listing(self, path: str) -> List[Dict]:
        with self._m["listing"].time():
            with self.lock.read():
                # listing a directory reads its children (r) and stats
                # them (x); "listing" a file is just a stat — traverse
                # only (ref: FSPermissionChecker READ_EXECUTE on dirs)
                is_dir = isinstance(self.fsdir.get_inode(path),
                                    INodeDirectory)
                self.check_access(
                    path, target=(PERM_R | PERM_X) if is_dir else 0)
                out = [st.to_wire() for st in self.fsdir.listing(path)]
        log_audit_event(True, "listStatus", path)
        return out

    def content_summary(self, path: str) -> Dict:
        from hadoop_tpu.dfs.namenode.inodes import iter_tree
        with self.lock.read():
            self.check_access(path)
            node = self.fsdir.get_inode(path)
            if node is None:
                raise FileNotFoundError(path)
            files = dirs = length = 0
            for n in iter_tree(node):
                if isinstance(n, INodeFile):
                    files += 1
                    length += n.length()
                else:
                    dirs += 1
            return {"files": files, "dirs": dirs, "length": length}

    # ------------------------------------------------------------ mutations

    def mkdirs(self, path: str) -> bool:
        self.check_path_names(path)
        with self._m["mkdirs"].time():
            owner = current_user().user_name
            with self.lock.write():
                self._check_not_safemode("mkdirs")
                self._check_mutable_path(path)
                if not self.fsdir.exists(path):
                    # WRITE on the deepest existing ancestor (ref:
                    # mkdirs' ancestorAccess=WRITE); an already-existing
                    # directory is the idempotent ensure-exists case and
                    # needs only traversal, like the reference
                    self.check_access(path, parent=PERM_W)
                    self._check_quota_locked(path, d_inodes=1, d_space=0)
                else:
                    self.check_access(path)
                self.fsdir.mkdirs(path, owner=owner)
                txid = self.editlog.log_edit(el.OP_MKDIR,
                                             {"p": path, "o": owner})
            self.editlog.log_sync(txid)
            log_audit_event(True, "mkdirs", path)
            return True

    def delete(self, path: str, recursive: bool) -> bool:
        with self._m["delete"].time():
            with self.lock.write():
                self._check_not_safemode("delete")
                self._check_mutable_path(path)
                self.check_access(
                    path, parent=PERM_W,
                    sub_dirs=(PERM_R | PERM_W | PERM_X) if recursive
                    else 0)
                removed = self._delete_locked(path, recursive)
                if not removed:
                    return False
                txid = self.editlog.log_edit(el.OP_DELETE,
                                             {"p": path, "r": recursive})
            self.editlog.log_sync(txid)
            log_audit_event(True, "delete", path)
            return True

    def _delete_locked(self, path: str, recursive: bool) -> bool:
        target = self.fsdir.get_inode(path)
        if target is not None:
            for n in iter_tree(target):
                if isinstance(n, INodeDirectory) and n.snapshots:
                    raise OSError(
                        f"cannot delete {path}: {n.full_path() or '/'} has "
                        f"{len(n.snapshots)} snapshot(s) — delete them "
                        "first (ref: the snapshottable-dir delete guard)")
        node = self.fsdir.delete(path, recursive)
        if node is None:
            return False
        # Open files anywhere under the deleted subtree lose their leases.
        self.leases.remove_under(path)
        blocks = collect_blocks(node)
        # Blocks captured by a snapshot stay alive until the last snapshot
        # referencing them is deleted (ref: snapshot block collection in
        # INodeFile.destroyAndCollectBlocks).
        pinned = self._pinned_block_ids_locked() if blocks else set()
        for b in blocks:
            if b.block_id not in pinned:
                self.bm.remove_block(b)
                # Provided blocks: drop the alias entry too, or it leaks
                # into every future image and keeps the external bytes
                # addressable by block id after delete.
                self.alias_map.pop(b.block_id, None)
        return True

    def rename(self, src: str, dst: str) -> bool:
        self.check_path_names(dst)
        with self._m["rename"].time():
            with self.lock.write():
                self._check_not_safemode("rename")
                self.check_access(src, parent=PERM_W)
                # move-INTO semantics: an existing dst directory IS the
                # parent the file lands in — WRITE must hold on it, not
                # on its parent (ref: FSDirRenameOp resolving the real
                # destination parent)
                if isinstance(self.fsdir.get_inode(dst), INodeDirectory):
                    self.check_access(dst, target=PERM_W)
                else:
                    self.check_access(dst, parent=PERM_W)
                self._check_mutable_path(src, dst)
                actual_dst = self.fsdir.rename(src, dst)
                self.leases.rename_path(src, actual_dst)
                # in-flight block recoveries follow the rename — their
                # phase-1 already stripped the lease, so a stale-keyed
                # entry would strand the file under-construction forever
                prefix = src.rstrip("/") + "/"
                for p in list(self._pending_recovery):
                    if p == src or p.startswith(prefix):
                        self._pending_recovery[actual_dst + p[len(src):]] \
                            = self._pending_recovery.pop(p)
                txid = self.editlog.log_edit(el.OP_RENAME,
                                             {"s": src, "d": dst})
            self.editlog.log_sync(txid)
            log_audit_event(True, "rename", src, dst)
            return True

    def set_replication(self, path: str, replication: int) -> bool:
        self._check_mutable_path(path)
        with self.lock.write():
            self._check_not_safemode("set replication")
            self.check_access(path, target=PERM_W)
            inode = self.fsdir.get_inode(path)
            if inode is None or not isinstance(inode, INodeFile):
                raise FileNotFoundError(path)
            inode.replication = replication
            for b in inode.blocks:
                info = self.bm.get(b.block_id)
                if info is not None:
                    info.expected_replication = replication
                    with self.bm._lock:
                        self.bm._update_needed_locked(info)
            txid = self.editlog.log_edit(el.OP_SET_REPLICATION,
                                         {"p": path, "rep": replication})
        self.editlog.log_sync(txid)
        return True

    # --------------------------------------------------------------- quotas

    def _check_quota_locked(self, path: str, d_inodes: int,
                            d_space: int) -> None:
        """Verify every quota-bearing ancestor of ``path`` can absorb the
        delta (ref: FSDirectory.verifyQuota). Quotas are rare, so usage is
        computed on demand rather than cached. Missing intermediate
        directories count toward the inode delta — they are about to be
        created too."""
        comps = [c for c in path.split("/") if c]
        node = self.fsdir.root
        chain = [node]
        for i, comp in enumerate(comps[:-1]):
            if not isinstance(node, INodeDirectory):
                break
            node = node.get_child(comp)
            if node is None:
                d_inodes += len(comps) - 1 - i  # dirs mkdirs will create
                break
            chain.append(node)
        for d in chain:
            if not isinstance(d, INodeDirectory):
                continue
            if d.ns_quota < 0 and d.space_quota < 0:
                continue
            inodes, space = subtree_counts(d)
            if 0 <= d.ns_quota < inodes + d_inodes:
                raise QuotaExceededError(
                    f"namespace quota of {d.full_path() or '/'} exceeded: "
                    f"quota={d.ns_quota} would-be={inodes + d_inodes}")
            if 0 <= d.space_quota < space + d_space:
                raise QuotaExceededError(
                    f"space quota of {d.full_path() or '/'} exceeded: "
                    f"quota={d.space_quota} would-be={space + d_space}")

    def set_quota(self, path: str, ns_quota: int, space_quota: int) -> None:
        """Ref: FSDirAttrOp.setQuota; -1 clears a dimension."""
        self.check_superuser("setQuota")
        self._check_mutable_path(path)
        with self.lock.write():
            self._check_not_safemode("set quota")
            node = self.fsdir.get_inode(path)
            if not isinstance(node, INodeDirectory):
                raise NotADirectoryError(f"quota target {path}")
            node.ns_quota = ns_quota
            node.space_quota = space_quota
            txid = self.editlog.log_edit(el.OP_SET_QUOTA, {
                "p": path, "nq": ns_quota, "sq": space_quota})
        self.editlog.log_sync(txid)

    # --------------------------------------------------------------- xattrs

    # ----------------------------------------------------- centralized cache

    def add_cache_directive(self, path: str) -> int:
        """Pin a file's blocks in DataNode memory (ref: namenode/
        CacheManager.java addDirective; pools collapse to flat
        directives). Returns the directive id."""
        with self.lock.write():
            self.check_access(path, target=PERM_R)
            node = self.fsdir.get_inode(path)
            if node is None or not isinstance(node, INodeFile):
                raise FileNotFoundError(path)
            did = self._next_cache_id
            self._next_cache_id += 1
            self.cache_directives[did] = path
            txid = self.editlog.log_edit(el.OP_ADD_CACHE_DIRECTIVE,
                                         {"id": did, "p": path})
        self.editlog.log_sync(txid)
        log_audit_event(True, "addCacheDirective", path)
        return did

    def remove_cache_directive(self, directive_id: int) -> bool:
        with self.lock.write():
            existing = self.cache_directives.get(directive_id)
            if existing is None:
                return False
            # same bar as adding one for that path: a user who cannot
            # read the file must not be able to evict its pinned blocks
            self.check_access(existing, target=PERM_R)
            gone = self.cache_directives.pop(directive_id, None)
            if gone is None:
                return False
            txid = self.editlog.log_edit(el.OP_REMOVE_CACHE_DIRECTIVE,
                                         {"id": directive_id})
        self.editlog.log_sync(txid)
        log_audit_event(True, "removeCacheDirective", gone)
        return True

    def list_cache_directives(self) -> Dict[int, str]:
        with self.lock.read():
            return dict(self.cache_directives)

    def cache_monitor_pass(self) -> None:
        """Reconcile DN cache state against the directives (ref:
        CacheReplicationMonitor.rescan)."""
        wanted: set = set()
        with self.lock.read():
            for path in self.cache_directives.values():
                node = self.fsdir.get_inode(path)
                if isinstance(node, INodeFile):
                    wanted.update(b.block_id for b in node.blocks)
        self.bm.reconcile_cache(wanted)

    # ------------------------------------------------------ encryption zones

    ZONE_XATTR = "system.crypto.zone"       # on the zone root: key name
    EDEK_XATTR = "system.crypto.edek"       # on each file: json FEInfo

    def _kms(self):
        """Lazy KMS client (ref: dfs.encryption.key.provider.uri — the NN
        generates EDEKs; it never sees plaintext DEKs)."""
        if getattr(self, "_kms_provider", None) is None:
            uri = self.conf.get("dfs.encryption.key.provider.uri", "")
            if not uri:
                return None
            from hadoop_tpu.crypto.kms import KMSKeyProvider
            addr = uri.split("://", 1)[-1].rstrip("/")
            self._kms_provider = KMSKeyProvider(addr, user="namenode")
        return self._kms_provider

    def create_encryption_zone(self, path: str, key_name: str) -> bool:
        """Mark an EMPTY directory as an encryption zone (ref:
        FSDirEncryptionZoneOp.createEncryptionZone — same constraints:
        directory, empty, not nested inside another zone)."""
        self.check_superuser("createEncryptionZone")
        if self._kms() is None:
            raise ValueError("no KMS configured "
                             "(dfs.encryption.key.provider.uri)")
        self._kms().get_current_key(key_name)  # must exist
        with self.lock.write():
            node = self._inode_or_raise(path)
            if not isinstance(node, INodeDirectory):
                raise NotADirectoryError(path)
            if node.children:
                raise OSError(f"cannot create zone on non-empty {path}")
            if self._zone_key_locked(path) is not None:
                raise OSError(f"{path} is already inside an encryption "
                              "zone")
            if node.xattrs is None:
                node.xattrs = {}
            node.xattrs[self.ZONE_XATTR] = key_name.encode()
            txid = self.editlog.log_edit(el.OP_SET_XATTR, {
                "p": path, "n": self.ZONE_XATTR, "v": key_name.encode()})
        self.editlog.log_sync(txid)
        log_audit_event(True, "createEncryptionZone", path)
        return True

    def _generate_edek_attr(self, key_name: str) -> bytes:
        """EDEK + metadata as the xattr payload (FileEncryptionInfo)."""
        import base64 as _b64
        import json as _json
        ekv = self._kms().generate_encrypted_key(key_name)
        return _json.dumps({
            "key": ekv.key_name, "version": ekv.key_version,
            "iv": _b64.b64encode(ekv.iv).decode(),
            "edek": _b64.b64encode(ekv.edek).decode(),
        }).encode()

    def _zone_key_locked(self, path: str) -> Optional[str]:
        """Nearest ancestor zone's key name (caller holds a lock)."""
        parts = [p for p in path.split("/") if p]
        for i in range(len(parts), -1, -1):
            prefix = "/" + "/".join(parts[:i]) if i else "/"
            node = self.fsdir.get_inode(prefix)
            if node is not None and node.xattrs and \
                    self.ZONE_XATTR in node.xattrs:
                return node.xattrs[self.ZONE_XATTR].decode()
        return None

    def list_encryption_zones(self) -> Dict[str, str]:
        """path → key name for every zone root (ref:
        FSDirEncryptionZoneOp.listEncryptionZones)."""
        out: Dict[str, str] = {}
        with self.lock.read():
            def walk(node, path: str) -> None:
                if node.xattrs and self.ZONE_XATTR in node.xattrs:
                    out[path or "/"] = \
                        node.xattrs[self.ZONE_XATTR].decode()
                if isinstance(node, INodeDirectory):
                    for name, child in node.children.items():
                        walk(child, f"{path}/{name}")
            walk(self.fsdir.root, "")
        return out

    def get_encryption_info(self, path: str) -> Optional[Dict]:
        """The file's FileEncryptionInfo for clients (ref:
        FSDirEncryptionZoneOp.getFileEncryptionInfo): the EDEK + key
        version the client hands to the KMS to obtain the DEK."""
        import json as _json
        with self.lock.read():
            node = self.fsdir.get_inode(path)
            if node is None or node.xattrs is None or \
                    self.EDEK_XATTR not in node.xattrs:
                return None
            return _json.loads(node.xattrs[self.EDEK_XATTR].decode())

    def set_xattr(self, path: str, name: str, value: bytes) -> None:
        """Ref: FSDirXAttrOp.setXAttr — names are namespaced."""
        self._check_mutable_path(path)
        ns = name.split(".", 1)[0]
        if ns not in ("user", "trusted", "system", "security", "raw"):
            raise ValueError(f"xattr name must be namespaced: {name!r}")
        if ns != "user":
            # trusted/system/security/raw carry internal state (EDEKs,
            # provenance): WRITE on the file must not allow forging it
            # (ref: XAttrPermissionFilter restricting these namespaces)
            self.check_superuser(f"setXAttr:{ns}")
        with self.lock.write():
            self.check_access(path, target=PERM_W)
            node = self._inode_or_raise(path)
            if node.xattrs is None:
                node.xattrs = {}
            node.xattrs[name] = value
            txid = self.editlog.log_edit(el.OP_SET_XATTR, {
                "p": path, "n": name, "v": value})
        self.editlog.log_sync(txid)

    def get_xattrs(self, path: str,
                   names: Optional[List[str]] = None) -> Dict[str, bytes]:
        with self.lock.read():
            self.check_access(path, target=PERM_R)
            node = self._inode_or_raise(path)
            attrs = node.xattrs or {}
            if names:
                missing = [n for n in names if n not in attrs]
                if missing:
                    raise ValueError(f"no such xattr(s) {missing} on {path}")
                return {n: attrs[n] for n in names}
            return dict(attrs)

    def remove_xattr(self, path: str, name: str) -> None:
        self._check_mutable_path(path)
        if name.split(".", 1)[0] != "user":
            self.check_superuser("removeXAttr:reserved")
        with self.lock.write():
            self.check_access(path, target=PERM_W)
            node = self._inode_or_raise(path)
            if not node.xattrs or name not in node.xattrs:
                raise ValueError(f"no xattr {name!r} on {path}")
            del node.xattrs[name]
            txid = self.editlog.log_edit(el.OP_REMOVE_XATTR, {
                "p": path, "n": name})
        self.editlog.log_sync(txid)

    # ----------------------------------------------------------------- acls

    def set_acl(self, path: str, entries: List[str]) -> None:
        """Replace the full ACL (ref: FSDirAclOp.setAcl). Entries are
        "type:name:perms" strings ("user:alice:rw-", "group::r--")."""
        self._check_mutable_path(path)
        for e in entries:
            if len(e.split(":")) != 3:
                raise ValueError(f"malformed ACL entry {e!r}")
        with self.lock.write():
            self.check_access(path, owner_only=True)
            node = self._inode_or_raise(path)
            node.acl = list(entries) or None
            txid = self.editlog.log_edit(el.OP_SET_ACL, {
                "p": path, "e": list(entries)})
        self.editlog.log_sync(txid)

    def get_acl(self, path: str) -> List[str]:
        with self.lock.read():
            self.check_access(path)
            return list(self._inode_or_raise(path).acl or [])

    def remove_acl(self, path: str) -> None:
        self.set_acl(path, [])

    # ------------------------------------------------------- storage policy

    def set_storage_policy(self, path: str, policy: str) -> None:
        self._check_mutable_path(path)
        if policy not in STORAGE_POLICIES:
            raise ValueError(
                f"unknown storage policy {policy!r}; known: "
                f"{STORAGE_POLICIES}")
        with self.lock.write():
            self.check_access(path, target=PERM_W)
            node = self._inode_or_raise(path)
            node.storage_policy = policy
            txid = self.editlog.log_edit(el.OP_SET_STORAGE_POLICY, {
                "p": path, "sp": policy})
        self.editlog.log_sync(txid)

    def get_storage_policy(self, path: str) -> str:
        """Effective (inherited) policy; HOT when unset."""
        with self.lock.read():
            node = self._inode_or_raise(path)
            while node is not None:
                if node.storage_policy:
                    return node.storage_policy
                node = node.parent
            return "HOT"

    def _inode_or_raise(self, path: str):
        node = self.fsdir.get_inode(path)
        if node is None:
            raise FileNotFoundError(path)
        return node

    # ------------------------------------------------------------ snapshots

    def allow_snapshot(self, path: str) -> None:
        """Ref: FSDirSnapshotOp.allowSnapshot."""
        self.check_superuser("allowSnapshot")
        with self.lock.write():
            node = self._inode_or_raise(path)
            if not isinstance(node, INodeDirectory):
                raise NotADirectoryError(path)
            node.snapshottable = True
            if node.snapshots is None:
                node.snapshots = {}
            txid = self.editlog.log_edit(el.OP_ALLOW_SNAPSHOT, {"p": path})
        self.editlog.log_sync(txid)

    def disallow_snapshot(self, path: str) -> None:
        self.check_superuser("disallowSnapshot")
        with self.lock.write():
            node = self._inode_or_raise(path)
            if not isinstance(node, INodeDirectory):
                raise NotADirectoryError(path)
            if node.snapshots:
                raise OSError(
                    f"{path} has {len(node.snapshots)} snapshot(s); delete "
                    "them first")
            node.snapshottable = False
            txid = self.editlog.log_edit(el.OP_DISALLOW_SNAPSHOT,
                                         {"p": path})
        self.editlog.log_sync(txid)

    def create_snapshot(self, path: str, name: str) -> str:
        """Ref: FSDirSnapshotOp.createSnapshot — captures the subtree's
        metadata; shared Block objects pin the data against deletion."""
        with self.lock.write():
            self._check_not_safemode("create snapshot")
            self.check_access(path, owner_only=True)
            node = self._inode_or_raise(path)
            if not isinstance(node, INodeDirectory) or not node.snapshottable:
                raise OSError(f"{path} is not snapshottable")
            if name in (node.snapshots or {}):
                raise FileExistsError(f"snapshot {name} exists on {path}")
            node.snapshots[name] = snapshot_copy(node)
            self._snapshot_count += 1
            txid = self.editlog.log_edit(el.OP_CREATE_SNAPSHOT, {
                "p": path, "n": name})
        self.editlog.log_sync(txid)
        return f"{path.rstrip('/')}/.snapshot/{name}"

    def delete_snapshot(self, path: str, name: str) -> None:
        with self.lock.write():
            self.check_access(path, owner_only=True)
            node = self._inode_or_raise(path)
            self._delete_snapshot_locked(node, path, name)
            txid = self.editlog.log_edit(el.OP_DELETE_SNAPSHOT, {
                "p": path, "n": name})
        self.editlog.log_sync(txid)

    def _delete_snapshot_locked(self, node, path: str, name: str) -> None:
        """Drop the snapshot and garbage-collect blocks referenced by
        nothing else — shared by the live path and edit replay so a
        standby's block map tracks the active's."""
        if not isinstance(node, INodeDirectory) or \
                name not in (node.snapshots or {}):
            raise FileNotFoundError(f"snapshot {name} on {path}")
        dropped = node.snapshots.pop(name)
        self._snapshot_count -= 1
        still = self._pinned_block_ids_locked()
        for n in iter_tree(self.fsdir.root):
            if isinstance(n, INodeFile):
                still.update(b.block_id for b in n.blocks)
        for b in collect_blocks(dropped):
            if b.block_id not in still:
                self.bm.remove_block(b)

    def rename_snapshot(self, path: str, old: str, new: str) -> None:
        with self.lock.write():
            self.check_access(path, owner_only=True)
            node = self._inode_or_raise(path)
            if not isinstance(node, INodeDirectory) or \
                    old not in (node.snapshots or {}):
                raise FileNotFoundError(f"snapshot {old} on {path}")
            if new in node.snapshots:
                raise FileExistsError(f"snapshot {new} on {path}")
            node.snapshots[new] = node.snapshots.pop(old)
            txid = self.editlog.log_edit(el.OP_RENAME_SNAPSHOT, {
                "p": path, "o": old, "n": new})
        self.editlog.log_sync(txid)

    def snapshot_diff(self, path: str, from_snap: str,
                      to_snap: str) -> Dict:
        """Paths created/deleted/modified between two snapshots ('' = the
        live tree). Ref: SnapshotDiffReport."""
        def index(root, prefix: str, out: Dict) -> Dict:
            # Keys are paths RELATIVE to the compared root — a snapshot
            # copy and the live dir share no parent chain, so absolute
            # paths would never align.
            out[prefix or "/"] = root
            if isinstance(root, INodeDirectory):
                for name, child in root.children.items():
                    index(child, f"{prefix}/{name}", out)
            return out

        with self.lock.read():
            node = self._inode_or_raise(path)
            if not isinstance(node, INodeDirectory):
                raise NotADirectoryError(path)

            def pick(name):
                if not name:
                    return node
                snap = (node.snapshots or {}).get(name)
                if snap is None:
                    raise FileNotFoundError(f"snapshot {name} on {path}")
                return snap

            a = index(pick(from_snap), "", {})
            b = index(pick(to_snap), "", {})
            base = path.rstrip("/")
            created = sorted(base + p for p in set(b) - set(a))
            deleted = sorted(base + p for p in set(a) - set(b))
            modified = sorted(
                base + p for p in set(a) & set(b)
                if isinstance(a[p], INodeFile) and isinstance(b[p], INodeFile)
                and ([blk.block_id for blk in a[p].blocks],
                     a[p].length()) != ([blk.block_id for blk in b[p].blocks],
                                        b[p].length()))
            return {"created": created, "deleted": deleted,
                    "modified": modified}

    def _pinned_block_ids_locked(self) -> set:
        """Block ids held by ANY snapshot anywhere in the namespace. The
        snapshot counter makes the no-snapshots case O(1) — deletes on a
        snapshot-free namespace must not pay a full tree walk."""
        if self._snapshot_count <= 0:
            return set()
        pinned = set()
        for n in iter_tree(self.fsdir.root):
            if isinstance(n, INodeDirectory) and n.snapshots:
                for snap in n.snapshots.values():
                    pinned.update(b.block_id for b in collect_blocks(snap))
        return pinned

    # ------------------------------------------------------ concat/truncate

    def concat(self, target: str, srcs: List[str]) -> None:
        """Move the blocks of ``srcs`` onto the end of ``target`` and
        delete the sources (ref: FSDirConcatOp — metadata-only append)."""
        with self.lock.write():
            self._check_not_safemode("concat")
            self._check_mutable_path(target, *srcs)
            self.check_access(target, target=PERM_W)
            for s in srcs:
                self.check_access(s, parent=PERM_W, target=PERM_W)
            if len(set(srcs)) != len(srcs) or target in srcs:
                raise ValueError(
                    f"concat sources must be distinct and exclude the "
                    f"target: {target} ← {srcs}")
            tgt = self._inode_or_raise(target)
            if not isinstance(tgt, INodeFile) or tgt.under_construction:
                raise OSError(f"concat target {target} not a closed file")
            if tgt.ec_policy:
                raise OSError("concat of striped files is unsupported")
            for s in srcs:
                src = self._inode_or_raise(s)
                if not isinstance(src, INodeFile) or src.under_construction:
                    raise OSError(f"concat source {s} not a closed file")
                if src.ec_policy:
                    raise OSError("concat of striped files is unsupported")
                if src.block_size != tgt.block_size:
                    raise OSError(f"block size mismatch: {s}")
            for s in srcs:
                src = self.fsdir.get_inode(s)
                for b in src.blocks:
                    info = self.bm.get(b.block_id)
                    if info is not None:
                        info.inode = tgt
                        info.expected_replication = tgt.replication
                tgt.blocks.extend(src.blocks)
                src.blocks = []
                self.fsdir.delete(s, recursive=False)
            tgt.mtime = time.time()
            txid = self.editlog.log_edit(el.OP_CONCAT, {
                "p": target, "s": list(srcs)})
        self.editlog.log_sync(txid)

    def truncate(self, path: str, new_length: int) -> bool:
        """Shrink a file (ref: FSDirTruncateOp). Whole blocks past the cut
        are dropped; the boundary block's length is trimmed in metadata —
        reads clamp to it, so no DN round trip is needed. Returns True
        (immediate completion; the reference's in-progress recovery case
        does not arise)."""
        with self.lock.write():
            self.check_access(path, target=PERM_W)
            self._check_not_safemode("truncate")
            self._check_mutable_path(path)
            inode = self._inode_or_raise(path)
            if not isinstance(inode, INodeFile):
                raise IsADirectoryError(path)
            if inode.under_construction:
                raise OSError(f"{path} is being written")
            if inode.ec_policy:
                raise OSError("truncate of striped files is unsupported")
            if new_length > inode.length():
                raise ValueError(
                    f"truncate length {new_length} > file length "
                    f"{inode.length()}")
            pinned = self._pinned_block_ids_locked()
            if any(b.block_id in pinned for b in inode.blocks):
                # Block objects are shared with snapshot copies; trimming
                # or dropping them would corrupt the captured version (the
                # reference versions the boundary block instead — here the
                # operation is refused, not silently wrong).
                raise OSError(
                    f"cannot truncate {path}: captured in a snapshot")
            pos = 0
            kept: List[Block] = []
            for b in inode.blocks:
                if pos >= new_length:
                    self.bm.remove_block(b)
                    continue
                if pos + b.num_bytes > new_length:
                    b.num_bytes = new_length - pos
                    info = self.bm.get(b.block_id)
                    if info is not None:
                        info.block.num_bytes = b.num_bytes
                kept.append(b)
                pos += b.num_bytes
            inode.blocks = kept
            inode.mtime = time.time()
            txid = self.editlog.log_edit(el.OP_TRUNCATE, {
                "p": path, "l": new_length,
                "b": [b.to_wire() for b in kept]})
        self.editlog.log_sync(txid)
        return True

    # ---------------------------------------------------------- erasure coding

    def _effective_ec_policy_locked(self, path: str) -> Optional[str]:
        """Nearest ancestor directory's EC policy (ref:
        FSDirErasureCodingOp.getErasureCodingPolicy — the EC xattr is
        inherited down the tree)."""
        comps = [c for c in path.split("/") if c]
        node = self.fsdir.root
        policy = node.ec_policy
        for comp in comps[:-1]:
            if not isinstance(node, INodeDirectory):
                break
            node = node.get_child(comp)
            if node is None:
                break
            if getattr(node, "ec_policy", None):
                policy = node.ec_policy
        return policy

    def set_ec_policy(self, path: str, policy_name: Optional[str]) -> bool:
        """Set (or clear, with None) the EC policy on a directory.
        Ref: FSNamesystem.setErasureCodingPolicy."""
        if policy_name:
            ec.get_policy(policy_name)  # validate
        with self.lock.write():
            self._check_not_safemode("set EC policy")
            self.check_access(path, target=PERM_W)
            node = self.fsdir.get_inode(path)
            if node is None:
                raise FileNotFoundError(path)
            if not isinstance(node, INodeDirectory):
                raise NotADirectoryError(
                    f"EC policy can only be set on directories: {path}")
            node.ec_policy = policy_name
            txid = self.editlog.log_edit(el.OP_SET_EC_POLICY, {
                "p": path, "ec": policy_name})
        self.editlog.log_sync(txid)
        return True

    def get_ec_policy(self, path: str) -> Optional[str]:
        """Effective policy for a path (file's own or inherited)."""
        with self.lock.read():
            node = self.fsdir.get_inode(path)
            if node is None:
                raise FileNotFoundError(path)
            own = getattr(node, "ec_policy", None)
            if own:
                return own
            return self._effective_ec_policy_locked(
                path.rstrip("/") + "/_" if isinstance(node, INodeDirectory)
                else path)

    def set_times(self, path: str, mtime: float, atime: float) -> None:
        self._check_mutable_path(path)
        with self.lock.write():
            self.check_access(path, target=PERM_W)
            inode = self.fsdir.get_inode(path)
            if inode is None:
                raise FileNotFoundError(path)
            if mtime >= 0:
                inode.mtime = mtime
            if atime >= 0:
                inode.atime = atime
            txid = self.editlog.log_edit(el.OP_SET_TIMES, {
                "p": path, "mt": mtime, "at": atime})
        self.editlog.log_sync(txid)

    def set_permission(self, path: str, permission: int) -> None:
        self._check_mutable_path(path)
        with self.lock.write():
            self.check_access(path, owner_only=True)
            inode = self.fsdir.get_inode(path)
            if inode is None:
                raise FileNotFoundError(path)
            inode.permission = permission
            txid = self.editlog.log_edit(el.OP_SET_PERMISSION, {
                "p": path, "pm": permission})
        self.editlog.log_sync(txid)

    def set_owner(self, path: str, owner: str, group: str) -> None:
        self._check_mutable_path(path)
        with self.lock.write():
            # traversal first (EXECUTE on every ancestor, like every
            # other op): a caller who cannot reach the path must not
            # learn whether it exists or who owns it
            self.check_access(path)
            inode = self.fsdir.get_inode(path)
            if inode is None:
                raise FileNotFoundError(path)
            self._check_set_owner_access(path, inode, owner, group)
            if owner:
                inode.owner = owner
            if group:
                inode.group = group
            txid = self.editlog.log_edit(el.OP_SET_OWNER, {
                "p": path, "o": owner, "g": group})
        self.editlog.log_sync(txid)

    # ----------------------------------------------------------- replay

    def _apply_edit(self, rec: Dict) -> None:
        """Replay one edit record at startup. Ref: FSEditLogLoader
        .applyEditLogOp."""
        op = rec["op"]
        # Track the id/stamp high-water marks across ALL replayed blocks —
        # including those of files later deleted, whose replicas may still
        # sit on DNs awaiting invalidation; reissuing their ids would collide.
        for bw in ([rec["b"]] if op == el.OP_ADD_BLOCK else
                   rec.get("b", []) if op in (el.OP_UPDATE_BLOCKS, el.OP_CLOSE)
                   else []):
            if isinstance(bw, dict):
                self._track_block_id(bw)
        if op == el.OP_ADD:
            if rec.get("ov") and self.fsdir.exists(rec["p"]):
                # create(overwrite=True) replaced an existing file; replay the
                # implicit delete (its blocks die with it — any replicas left
                # on DNs are invalidated as unknown at report time). Pinned
                # blocks survive, exactly like the live path.
                gone = self.fsdir.delete(rec["p"], recursive=False)
                if gone is not None:
                    pinned = self._pinned_block_ids_locked()
                    for b in collect_blocks(gone):
                        if b.block_id not in pinned:
                            self.bm.remove_block(b)
                            self.alias_map.pop(b.block_id, None)
                holder = self.leases.holder_of(rec["p"])
                if holder:
                    self.leases.remove_lease(holder, rec["p"])
            inode = self.fsdir.add_file(rec["p"], rec["rep"], rec["bs"],
                                        owner=rec.get("o", ""),
                                        ec_policy=rec.get("ec"))
            inode.under_construction = True
            inode.client_name = rec.get("cl")
            if inode.client_name:
                self.leases.add_lease(inode.client_name, rec["p"])
        elif op == el.OP_PROVIDED_FILE:
            inode = self.fsdir.add_file(rec["p"], 1, rec["bs"],
                                        owner=rec.get("o", ""))
            inode.under_construction = False
            off = 0
            for bw in rec.get("b", []):
                blk = Block.from_wire(bw)
                self._track_block_id(bw)
                inode.blocks.append(blk)
                self.alias_map[blk.block_id] = {
                    "uri": rec["uri"], "offset": off,
                    "length": blk.num_bytes}
                off += blk.num_bytes
        elif op == el.OP_ADD_BLOCK:
            inode = self.fsdir.get_inode(rec["p"])
            if isinstance(inode, INodeFile):
                blk = Block.from_wire(rec["b"])
                inode.blocks.append(blk)
                info = self._register_block_locked(inode, blk)
                info.under_construction = True
        elif op == el.OP_UPDATE_BLOCKS:
            inode = self.fsdir.get_inode(rec["p"])
            if isinstance(inode, INodeFile):
                new_blocks = [Block.from_wire(b) for b in rec["b"]]
                kept = {b.block_id for b in new_blocks}
                for old in inode.blocks:
                    if old.block_id not in kept:
                        self.bm.remove_block(old)
                inode.blocks = new_blocks
                for b in inode.blocks:
                    self._register_block_locked(inode, b)
        elif op == el.OP_CLOSE:
            inode = self.fsdir.get_inode(rec["p"])
            if isinstance(inode, INodeFile):
                inode.blocks = [Block.from_wire(b) for b in rec["b"]]
                inode.under_construction = False
                if inode.client_name:
                    self.leases.remove_lease(inode.client_name, rec["p"])
                    inode.client_name = None
                for b in inode.blocks:
                    self._register_block_locked(inode, b)
                    self.bm.complete_block(b)
        elif op == el.OP_MKDIR:
            self.fsdir.mkdirs(rec["p"], owner=rec.get("o", ""))
        elif op == el.OP_DELETE:
            node = self.fsdir.delete(rec["p"], rec.get("r", True))
            if node is not None:
                self.leases.remove_under(rec["p"])
                pinned = self._pinned_block_ids_locked()
                for b in collect_blocks(node):
                    if b.block_id not in pinned:
                        self.bm.remove_block(b)
        elif op == el.OP_RENAME:
            actual = self.fsdir.rename(rec["s"], rec["d"])
            self.leases.rename_path(rec["s"], actual)
        elif op == el.OP_SET_REPLICATION:
            inode = self.fsdir.get_inode(rec["p"])
            if isinstance(inode, INodeFile):
                inode.replication = rec["rep"]
        elif op == el.OP_SET_TIMES:
            inode = self.fsdir.get_inode(rec["p"])
            if inode is not None:
                if rec["mt"] >= 0:
                    inode.mtime = rec["mt"]
                if rec["at"] >= 0:
                    inode.atime = rec["at"]
        elif op == el.OP_SET_PERMISSION:
            inode = self.fsdir.get_inode(rec["p"])
            if inode is not None:
                inode.permission = rec["pm"]
        elif op == el.OP_SET_OWNER:
            inode = self.fsdir.get_inode(rec["p"])
            if inode is not None:
                inode.owner = rec.get("o") or inode.owner
                inode.group = rec.get("g") or inode.group
        elif op == el.OP_SET_EC_POLICY:
            node = self.fsdir.get_inode(rec["p"])
            if isinstance(node, INodeDirectory):
                node.ec_policy = rec.get("ec")
        elif op == el.OP_SET_QUOTA:
            node = self.fsdir.get_inode(rec["p"])
            if isinstance(node, INodeDirectory):
                node.ns_quota = rec.get("nq", -1)
                node.space_quota = rec.get("sq", -1)
        elif op == el.OP_ADD_CACHE_DIRECTIVE:
            self.cache_directives[rec["id"]] = rec["p"]
            self._next_cache_id = max(self._next_cache_id, rec["id"] + 1)
        elif op == el.OP_REMOVE_CACHE_DIRECTIVE:
            self.cache_directives.pop(rec["id"], None)
        elif op == el.OP_SET_XATTR:
            node = self.fsdir.get_inode(rec["p"])
            if node is not None:
                if node.xattrs is None:
                    node.xattrs = {}
                node.xattrs[rec["n"]] = rec["v"]
        elif op == el.OP_REMOVE_XATTR:
            node = self.fsdir.get_inode(rec["p"])
            if node is not None and node.xattrs:
                node.xattrs.pop(rec["n"], None)
        elif op == el.OP_SET_ACL:
            node = self.fsdir.get_inode(rec["p"])
            if node is not None:
                node.acl = list(rec.get("e") or []) or None
        elif op == el.OP_SET_STORAGE_POLICY:
            node = self.fsdir.get_inode(rec["p"])
            if node is not None:
                node.storage_policy = rec.get("sp")
        elif op == el.OP_ALLOW_SNAPSHOT:
            node = self.fsdir.get_inode(rec["p"])
            if isinstance(node, INodeDirectory):
                node.snapshottable = True
                if node.snapshots is None:
                    node.snapshots = {}
        elif op == el.OP_DISALLOW_SNAPSHOT:
            node = self.fsdir.get_inode(rec["p"])
            if isinstance(node, INodeDirectory):
                node.snapshottable = False
        elif op == el.OP_CREATE_SNAPSHOT:
            node = self.fsdir.get_inode(rec["p"])
            if isinstance(node, INodeDirectory) and node.snapshottable:
                node.snapshots[rec["n"]] = snapshot_copy(node)
                self._snapshot_count += 1
        elif op == el.OP_DELETE_SNAPSHOT:
            node = self.fsdir.get_inode(rec["p"])
            try:
                self._delete_snapshot_locked(node, rec["p"], rec["n"])
            except FileNotFoundError:
                pass
        elif op == el.OP_RENAME_SNAPSHOT:
            node = self.fsdir.get_inode(rec["p"])
            if isinstance(node, INodeDirectory) and node.snapshots and \
                    rec["o"] in node.snapshots:
                node.snapshots[rec["n"]] = node.snapshots.pop(rec["o"])
        elif op == el.OP_CONCAT:
            tgt = self.fsdir.get_inode(rec["p"])
            if isinstance(tgt, INodeFile):
                for s in rec.get("s", []):
                    src = self.fsdir.get_inode(s)
                    if isinstance(src, INodeFile):
                        for b in src.blocks:
                            info = self.bm.get(b.block_id)
                            if info is not None:
                                info.inode = tgt
                                info.expected_replication = tgt.replication
                        tgt.blocks.extend(src.blocks)
                        src.blocks = []
                        self.fsdir.delete(s, recursive=False)
        elif op == el.OP_TRUNCATE:
            inode = self.fsdir.get_inode(rec["p"])
            if isinstance(inode, INodeFile):
                new_blocks = [Block.from_wire(b) for b in rec.get("b", [])]
                kept = {b.block_id for b in new_blocks}
                for old in inode.blocks:
                    if old.block_id not in kept:
                        self.bm.remove_block(old)
                inode.blocks = new_blocks
                for b in inode.blocks:
                    info = self.bm.get(b.block_id)
                    if info is not None:
                        info.block.num_bytes = b.num_bytes
        elif op == el.OP_SET_GENSTAMP:
            with self._id_lock:
                self._gen_stamp = max(self._gen_stamp, rec["gs"])
        else:
            log.warning("Unknown edit op %r (txid %d) — skipped", op, rec["t"])
