"""NameNode high availability: states, tailing, checkpointing, failover.

Parity with the reference's HA machinery (ref: server/namenode/ha/
EditLogTailer.java:73 + :324 doTailEdits, StandbyCheckpointer.java:64 +
:194 doCheckpoint, StandbyState/ActiveState/ObserverState;
ha/ZKFailoverController.java, HealthMonitor.java):

- **States**: ``active`` serves everything and writes the journal;
  ``standby`` rejects client ops (StandbyError → client fails over) while
  tailing the shared QJM log; ``observer`` additionally serves reads with
  state-id alignment (msync).
- **EditLogTailer**: standby/observer thread applying newly committed
  quorum edits to the local namesystem.
- **StandbyCheckpointer**: periodic fsimage save on the standby — the
  active never pauses to checkpoint.
- **FailoverController**: per-NN elector thread renewing the majority
  lease on the JournalNodes; grabbing it promotes the local NN (journal
  epoch fencing makes a deposed active harmless), losing it demotes.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from hadoop_tpu.ipc.errors import StandbyError
from hadoop_tpu.util.misc import Daemon

log = logging.getLogger(__name__)

ACTIVE = "active"
STANDBY = "standby"
OBSERVER = "observer"


class EditLogTailer:
    """Ref: ha/EditLogTailer.java — keeps a non-active NN's namespace
    caught up by replaying committed quorum edits."""

    def __init__(self, fsn, interval_s: float = 1.0):
        self.fsn = fsn
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_applied_txid = 0

    def start(self, from_txid: int) -> None:
        self.stop()  # never two tailer threads over one namesystem
        self.last_applied_txid = from_txid
        self._stop.clear()
        self._thread = Daemon(self._run, "edit-log-tailer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def catch_up(self) -> int:
        """Synchronous final tail (used during transition to active).
        Returns the last applied txid."""
        self.do_tail()
        return self.last_applied_txid

    def do_tail(self) -> int:
        """One tailing pass. Ref: EditLogTailer.doTailEdits:324.

        The journal read happens BEFORE taking the namesystem write lock:
        for a quorum journal the read is an RPC fan-out with multi-second
        timeouts when a JN is down, and holding the write lock across it
        would stall observer reads for the whole timeout (the reference
        likewise streams edits outside the lock and applies under it)."""
        edits = list(self.fsn.editlog.journal.read_edits(
            self.last_applied_txid + 1))
        applied = 0
        if edits:
            with self.fsn.lock.write():
                for rec in edits:
                    if rec["t"] <= self.last_applied_txid:
                        continue  # lost race with a concurrent catch-up
                    self.fsn._apply_edit(rec)
                    self.last_applied_txid = rec["t"]
                    applied += 1
        if applied:
            log.debug("Tailed %d edits (through txid %d)", applied,
                      self.last_applied_txid)
        return applied

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.do_tail()
            except Exception:
                log.exception("Edit tailing pass failed")


class StandbyCheckpointer:
    """Ref: ha/StandbyCheckpointer.java — the standby saves images so the
    active never has to."""

    def __init__(self, fsn, tailer: EditLogTailer,
                 period_s: float = 3600.0, txns: int = 1_000_000):
        self.fsn = fsn
        self.tailer = tailer
        self.period_s = period_s
        self.txns = txns
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_ckpt_txid = 0

    def start(self) -> None:
        self.stop()
        self._stop.clear()
        self._last_ckpt_txid = self.tailer.last_applied_txid
        self._thread = Daemon(self._run, "standby-checkpointer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _run(self) -> None:
        last_time = time.monotonic()
        while not self._stop.wait(min(self.period_s, 5.0)):
            try:
                behind = self.tailer.last_applied_txid - self._last_ckpt_txid
                if behind >= self.txns or (
                        behind > 0 and
                        time.monotonic() - last_time >= self.period_s):
                    self.do_checkpoint()
                    last_time = time.monotonic()
            except Exception:
                log.exception("Standby checkpoint failed")

    def do_checkpoint(self) -> str:
        """Ref: StandbyCheckpointer.doCheckpoint:194 — save the image at
        the tailed txid. (No upload step: every NN reads the same image
        directory convention; the image is node-local like the reference's,
        and a restarted peer replays the quorum journal past its own
        newest image.)"""
        with self.fsn.lock.write():
            txid = self.tailer.last_applied_txid
            path = self.fsn.image.save(self.fsn.fsdir, txid,
                                       self.fsn.image_extra())
        self.fsn.image.purge_old()
        self._last_ckpt_txid = txid
        log.info("Standby checkpoint at txid %d → %s", txid, path)
        return path


class FailoverController:
    """Automatic failover: elect via the JN majority lease, promote/demote
    the local NN. Ref: ha/ZKFailoverController.java + HealthMonitor — one
    in-process controller per NameNode instead of a sidecar daemon."""

    def __init__(self, namenode, lease, check_interval_s: float = 1.0):
        self.nn = namenode
        self.lease = lease
        self.check_interval_s = check_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = Daemon(self._run, f"failover-controller-{self.nn.nn_id}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            try:
                self._one_round()
            except Exception:
                log.exception("Failover controller round failed")

    def _one_round(self) -> None:
        if self.nn.ha_state == OBSERVER:
            return  # observers never contend for the active lease
        healthy = self.nn.is_healthy()
        if not healthy:
            if self.nn.ha_state == ACTIVE:
                log.warning("Local NN unhealthy; releasing active lease")
                self.lease.release()
                self.nn.transition_to_standby()
            return
        if self.lease.try_acquire():
            if self.nn.ha_state != ACTIVE:
                log.info("Won active lease; promoting %s", self.nn.nn_id)
                self.nn.transition_to_active()
        else:
            if self.nn.ha_state == ACTIVE:
                log.warning("Lost active lease; demoting %s", self.nn.nn_id)
                self.nn.transition_to_standby()


def check_operation(ha_state: str, is_write: bool) -> None:
    """Gate an RPC by HA state (ref: NameNode.checkOperation /
    StandbyException paths)."""
    if ha_state == ACTIVE:
        return
    if ha_state == OBSERVER and not is_write:
        return
    raise StandbyError(
        f"Operation category {'WRITE' if is_write else 'READ'} is not "
        f"supported in state {ha_state}")
