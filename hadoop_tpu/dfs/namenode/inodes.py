"""In-memory namespace: inode tree + directory operations.

Parity with the reference namespace core (ref: server/namenode/INode.java,
INodeFile.java, INodeDirectory.java, FSDirectory.java (2,077 LoC)): a rooted
tree of directories and files, files holding ordered block lists, with
owner/permission/times metadata. All mutations happen under the namesystem
lock (see fsnamesystem.py) — this module is lock-free by contract.
"""

from __future__ import annotations

import time
from typing import Dict, Iterator, List, Optional

from hadoop_tpu.dfs.protocol.records import Block, FileStatus


class INode:
    __slots__ = ("name", "parent", "mtime", "atime", "owner", "group",
                 "permission", "xattrs", "acl", "storage_policy")

    def __init__(self, name: str, owner: str = "", group: str = "",
                 permission: int = 0o755):
        self.name = name
        self.parent: Optional["INodeDirectory"] = None
        self.mtime = time.time()
        self.atime = self.mtime
        self.owner = owner
        self.group = group
        self.permission = permission
        # Extended attributes (ref: XAttrFeature; user./trusted./system.
        # namespaces enforced at the RPC layer).
        self.xattrs: Optional[Dict[str, bytes]] = None
        # ACL entries beyond the permission bits (ref: AclFeature):
        # list of "type:name:perms" strings, e.g. "user:alice:rw-".
        self.acl: Optional[List[str]] = None
        # Storage policy name (ref: BlockStoragePolicySuite; HOT default,
        # inherited from the nearest ancestor that sets one).
        self.storage_policy: Optional[str] = None

    @property
    def is_dir(self) -> bool:
        return isinstance(self, INodeDirectory)

    def full_path(self) -> str:
        parts: List[str] = []
        node: Optional[INode] = self
        while node is not None and node.parent is not None:
            parts.append(node.name)
            node = node.parent
        return "/" + "/".join(reversed(parts))


class INodeFile(INode):
    __slots__ = ("replication", "block_size", "blocks", "under_construction",
                 "client_name", "ec_policy")

    def __init__(self, name: str, replication: int, block_size: int,
                 owner: str = "", permission: int = 0o644,
                 ec_policy: Optional[str] = None):
        super().__init__(name, owner=owner, permission=permission)
        self.replication = replication
        self.block_size = block_size
        self.blocks: List[Block] = []
        self.under_construction = False
        self.client_name: Optional[str] = None  # lease holder while open
        # Striped layout policy name, fixed at create (ref: INodeFile's
        # erasure-coding-policy ID in its header).
        self.ec_policy: Optional[str] = ec_policy

    def length(self) -> int:
        return sum(b.num_bytes for b in self.blocks)

    def last_block(self) -> Optional[Block]:
        return self.blocks[-1] if self.blocks else None

    def status(self, path: Optional[str] = None) -> FileStatus:
        return FileStatus(path if path is not None else self.full_path(),
                          False, self.length(), self.replication,
                          self.block_size, self.mtime, self.atime,
                          self.owner, self.group, self.permission,
                          ec_policy=self.ec_policy)


class INodeDirectory(INode):
    __slots__ = ("children", "ec_policy", "ns_quota", "space_quota",
                 "snapshottable", "snapshots")

    def __init__(self, name: str, owner: str = "", permission: int = 0o755):
        super().__init__(name, owner=owner, permission=permission)
        self.children: Dict[str, INode] = {}
        # EC policy set on this directory; inherited by files created under
        # it (ref: ErasureCodingPolicyManager + the EC xattr on dirs).
        self.ec_policy: Optional[str] = None
        # Quotas (ref: DirectoryWithQuotaFeature): -1 = unset.
        self.ns_quota: int = -1      # max inodes in subtree
        self.space_quota: int = -1   # max bytes × replication in subtree
        # Snapshots (ref: DirectorySnapshottableFeature): name → captured
        # root (an immutable deep copy of this subtree's metadata; block
        # objects are shared, the snapshot pins them against deletion).
        self.snapshottable = False
        self.snapshots: Optional[Dict[str, "INodeDirectory"]] = None

    def add_child(self, node: INode) -> None:
        node.parent = self
        self.children[node.name] = node
        self.mtime = time.time()

    def remove_child(self, name: str) -> Optional[INode]:
        node = self.children.pop(name, None)
        if node is not None:
            node.parent = None
            self.mtime = time.time()
        return node

    def get_child(self, name: str) -> Optional[INode]:
        return self.children.get(name)

    def status(self, path: Optional[str] = None) -> FileStatus:
        return FileStatus(path if path is not None else self.full_path(),
                          True, 0, 0, 0, self.mtime, self.atime, self.owner,
                          self.group, self.permission)


def _components(path: str) -> List[str]:
    # NOTE: deliberately permissive about "." / ".." — this resolver is
    # shared with edit-log REPLAY and with cleanup of inodes a pre-fix
    # tree may hold; name VALIDITY is enforced at the name-creating op
    # entries instead (FSNamesystem.check_path_names, the reference's
    # DFSUtil.isValidName placement).
    if not path.startswith("/"):
        raise ValueError(f"path must be absolute: {path!r}")
    return [c for c in path.split("/") if c]


SNAPSHOT_DIR = ".snapshot"


def snapshot_copy(node: INode) -> INode:
    """Immutable metadata copy of a subtree for a snapshot (ref:
    snapshot/Snapshot.java's root copy). Block objects are shared — the
    snapshot pins them, it does not duplicate data."""
    if isinstance(node, INodeDirectory):
        cp = INodeDirectory(node.name, owner=node.owner,
                            permission=node.permission)
        cp.group = node.group
        cp.mtime, cp.atime = node.mtime, node.atime
        cp.ec_policy = node.ec_policy
        cp.storage_policy = node.storage_policy
        cp.xattrs = dict(node.xattrs) if node.xattrs else None
        cp.acl = list(node.acl) if node.acl else None
        for child in node.children.values():
            cp.add_child(snapshot_copy(child))
        return cp
    f: INodeFile = node  # type: ignore[assignment]
    cp = INodeFile(f.name, f.replication, f.block_size, owner=f.owner,
                   permission=f.permission, ec_policy=f.ec_policy)
    cp.group = f.group
    cp.mtime, cp.atime = f.mtime, f.atime
    cp.storage_policy = f.storage_policy
    cp.xattrs = dict(f.xattrs) if f.xattrs else None
    cp.acl = list(f.acl) if f.acl else None
    if f.under_construction:
        # The trailing blocks of an open file are still mutated in place
        # (commit/recovery update num_bytes/gen_stamp on the shared
        # objects) — value-copy so the snapshot stays frozen at the
        # capture point. Finalized files' blocks are immutable and safe
        # to share.
        cp.blocks = [Block(b.block_id, b.gen_stamp, b.num_bytes)
                     for b in f.blocks]
    else:
        cp.blocks = list(f.blocks)
    return cp


def subtree_counts(node: INode) -> tuple:
    """(inodes, space) where space = Σ file length × replication — the
    quota dimensions (ref: QuotaCounts)."""
    inodes = 0
    space = 0
    for n in iter_tree(node):
        inodes += 1
        if isinstance(n, INodeFile):
            rep = 1 if n.ec_policy else max(1, n.replication)
            space += n.length() * rep
    return inodes, space


class FSDirectory:
    """Path-indexed view over the inode tree. Ref: FSDirectory.java."""

    def __init__(self):
        self.root = INodeDirectory("")
        self._inode_count = 1

    # ------------------------------------------------------------- resolve

    def get_inode(self, path: str) -> Optional[INode]:
        node: INode = self.root
        comps = _components(path)
        i = 0
        while i < len(comps):
            comp = comps[i]
            if not isinstance(node, INodeDirectory):
                return None
            if comp == SNAPSHOT_DIR and node.snapshottable:
                # /dir/.snapshot/<name>/... resolves inside the captured
                # subtree (ref: INodeDirectory.getChild's snapshot path).
                if i + 1 >= len(comps):
                    return node  # "/dir/.snapshot" itself → listed specially
                snap = (node.snapshots or {}).get(comps[i + 1])
                if snap is None:
                    return None
                node = snap
                i += 2
                continue
            nxt = node.get_child(comp)
            if nxt is None:
                return None
            node = nxt
            i += 1
        return node

    def get_parent(self, path: str) -> Optional[INodeDirectory]:
        comps = _components(path)
        if not comps:
            return None
        node: INode = self.root
        for comp in comps[:-1]:
            if not isinstance(node, INodeDirectory):
                return None
            nxt = node.get_child(comp)
            if nxt is None:
                return None
            node = nxt
        return node if isinstance(node, INodeDirectory) else None

    def exists(self, path: str) -> bool:
        return self.get_inode(path) is not None

    # ------------------------------------------------------------ mutations

    def mkdirs(self, path: str, owner: str = "",
               permission: int = 0o755) -> INodeDirectory:
        """Create all missing path components. Ref: FSDirectory.mkdirs."""
        node: INode = self.root
        for comp in _components(path):
            if not isinstance(node, INodeDirectory):
                raise NotADirectoryError(
                    f"{node.full_path()} is a file in path {path}")
            nxt = node.get_child(comp)
            if nxt is None:
                nxt = INodeDirectory(comp, owner=owner, permission=permission)
                node.add_child(nxt)
                self._inode_count += 1
            node = nxt
        if not isinstance(node, INodeDirectory):
            raise NotADirectoryError(f"{path} exists as a file")
        return node

    def add_file(self, path: str, replication: int, block_size: int,
                 owner: str = "", permission: int = 0o644,
                 ec_policy: Optional[str] = None) -> INodeFile:
        comps = _components(path)
        if not comps:
            raise IsADirectoryError("cannot create file at /")
        parent = self.mkdirs("/" + "/".join(comps[:-1]), owner=owner)
        if parent.get_child(comps[-1]) is not None:
            raise FileExistsError(f"{path} already exists")
        f = INodeFile(comps[-1], replication, block_size, owner=owner,
                      permission=permission, ec_policy=ec_policy)
        parent.add_child(f)
        self._inode_count += 1
        return f

    def delete(self, path: str, recursive: bool) -> Optional[INode]:
        """Detach the subtree at path; caller collects its blocks.
        Ref: FSDirectory.delete."""
        node = self.get_inode(path)
        if node is None:
            return None
        if node is self.root:
            raise PermissionError("cannot delete /")
        if isinstance(node, INodeDirectory) and node.children and not recursive:
            raise OSError(f"{path} is non-empty; use recursive delete")
        node.parent.remove_child(node.name)
        self._inode_count -= sum(1 for _ in iter_tree(node))
        return node

    def rename(self, src: str, dst: str) -> str:
        """POSIX-ish rename. Ref: FSDirectory.renameTo (RENAME semantics:
        fail if dst exists; moving into an existing dir targets dst/basename).
        Returns the actual destination path (after into-dir adjustment)."""
        node = self.get_inode(src)
        if node is None:
            raise FileNotFoundError(f"rename source {src} not found")
        if node is self.root:
            raise PermissionError("cannot rename /")
        dst_node = self.get_inode(dst)
        if isinstance(dst_node, INodeDirectory):
            dst = dst.rstrip("/") + "/" + node.name
            dst_node = self.get_inode(dst)
        if dst_node is not None:
            raise FileExistsError(f"rename target {dst} exists")
        if dst.startswith(src.rstrip("/") + "/"):
            raise ValueError(f"cannot rename {src} under itself: {dst}")
        dst_parent = self.get_parent(dst)
        if dst_parent is None:
            raise FileNotFoundError(f"rename target parent missing: {dst}")
        node.parent.remove_child(node.name)
        node.name = _components(dst)[-1]
        dst_parent.add_child(node)
        return dst

    # ------------------------------------------------------------- queries

    def listing(self, path: str) -> List[FileStatus]:
        base = path.rstrip("/")
        if base.endswith("/" + SNAPSHOT_DIR):
            parent = self.get_inode(base[:-len(SNAPSHOT_DIR) - 1] or "/")
            if not isinstance(parent, INodeDirectory) or \
                    not parent.snapshottable:
                raise FileNotFoundError(path)
            return [snap.status(f"{base}/{name}")
                    for name, snap in sorted((parent.snapshots or {}).items())]
        node = self.get_inode(path)
        if node is None:
            raise FileNotFoundError(path)
        if isinstance(node, INodeDirectory):
            return [child.status(f"{base}/{name}" if base else f"/{name}")
                    for name, child in sorted(node.children.items())]
        return [node.status(path)]

    def num_inodes(self) -> int:
        return self._inode_count


def iter_tree(node: INode) -> Iterator[INode]:
    yield node
    if isinstance(node, INodeDirectory):
        for child in list(node.children.values()):
            yield from iter_tree(child)


def collect_blocks(node: INode) -> List[Block]:
    out: List[Block] = []
    for n in iter_tree(node):
        if isinstance(n, INodeFile):
            out.extend(n.blocks)
    return out
